"""Tests for the fused Pallas kernel tier (ops/pallas/fused_ops.py):
RMSNorm fwd/bwd and single-pass AdamW, in interpret mode on CPU, plus the
fused rope functional. Reference: phi/kernels/fusion fused_rms_norm /
fused_adam / fused_rope."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.fused_ops import adamw_pallas, rms_norm_pallas


def _ref_rmsnorm(x, w, eps=1e-6):
    var = np.mean(x.astype(np.float64) ** 2, axis=-1, keepdims=True)
    return (x / np.sqrt(var + eps) * w).astype(np.float32)


def test_rmsnorm_pallas_forward_matches_reference():
    rng = np.random.RandomState(0)
    x = rng.randn(6, 4, 256).astype(np.float32)
    w = rng.rand(256).astype(np.float32) + 0.5
    out = rms_norm_pallas(jnp.asarray(x), jnp.asarray(w), 1e-6, True)
    np.testing.assert_allclose(np.asarray(out), _ref_rmsnorm(x, w),
                               rtol=1e-4, atol=1e-5)


def test_rmsnorm_pallas_gradients_match_autodiff():
    rng = np.random.RandomState(1)
    x = rng.randn(8, 128).astype(np.float32)
    w = rng.rand(128).astype(np.float32) + 0.5

    def ref(x_, w_):
        var = jnp.mean(jnp.square(x_), axis=-1, keepdims=True)
        return jnp.sum(jnp.sin(x_ * jax.lax.rsqrt(var + 1e-6) * w_))

    def fused(x_, w_):
        return jnp.sum(jnp.sin(rms_norm_pallas(x_, w_, 1e-6, True)))

    gx_ref, gw_ref = jax.grad(ref, argnums=(0, 1))(jnp.asarray(x),
                                                   jnp.asarray(w))
    gx, gw = jax.grad(fused, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=1e-3, atol=1e-4)


def test_rmsnorm_pallas_bf16():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 128), jnp.bfloat16)
    w = jnp.asarray(rng.rand(128) + 0.5, jnp.bfloat16)
    out = rms_norm_pallas(x, w, 1e-6, True)
    assert out.dtype == jnp.bfloat16
    ref = _ref_rmsnorm(np.asarray(x, np.float32), np.asarray(w, np.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=3e-2, atol=3e-2)


def test_rmsnorm_routing_through_functional():
    # CPU: routing must stay on the XLA path and still be correct
    from paddle_tpu.nn import functional as F
    x = paddle.to_tensor(np.random.RandomState(3).randn(2, 8, 128)
                         .astype(np.float32))
    w = paddle.to_tensor(np.random.RandomState(4).rand(128)
                         .astype(np.float32))
    out = F.rms_norm(x, w)
    ref = _ref_rmsnorm(np.asarray(x._data), np.asarray(w._data))
    np.testing.assert_allclose(np.asarray(out._data), ref, rtol=1e-4,
                               atol=1e-5)


def _ref_adamw(p, m, v, g, lr, b1, b2, eps, wd, t):
    m2 = b1 * m + (1 - b1) * g
    v2 = b2 * v + (1 - b2) * g * g
    mh = m2 / (1 - b1 ** t)
    vh = v2 / (1 - b2 ** t)
    p2 = p * (1 - lr * wd) - lr * mh / (np.sqrt(vh) + eps)
    return p2, m2, v2


@pytest.mark.parametrize("shape", [(1000,), (33, 77), (4, 128, 128)])
def test_adamw_pallas_matches_reference(shape):
    rng = np.random.RandomState(0)
    p = rng.randn(*shape).astype(np.float32)
    m = rng.randn(*shape).astype(np.float32) * 0.1
    v = np.abs(rng.randn(*shape)).astype(np.float32) * 0.01
    g = rng.randn(*shape).astype(np.float32)
    lr, b1, b2, eps, wd, t = 1e-3, 0.9, 0.999, 1e-8, 0.01, 3

    p2, m2, v2 = adamw_pallas(
        jnp.asarray(p), jnp.asarray(m), jnp.asarray(v), jnp.asarray(g),
        lr=lr, beta1=b1, beta2=b2, eps=eps, weight_decay=wd,
        beta1_pow=b1 ** t, beta2_pow=b2 ** t, interpret=True)
    rp, rm, rv = _ref_adamw(p, m, v, g, lr, b1, b2, eps, wd, t)
    np.testing.assert_allclose(np.asarray(p2), rp, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m2), rm, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v2), rv, rtol=1e-5, atol=1e-6)


def test_adamw_pallas_multi_step_training_converges():
    # quadratic bowl: p -> 0 under repeated fused updates
    p = jnp.asarray(np.ones(512, np.float32) * 5.0)
    m = jnp.zeros(512, jnp.float32)
    v = jnp.zeros(512, jnp.float32)
    for t in range(1, 60):
        g = 2 * p  # d/dp p^2
        p, m, v = adamw_pallas(p, m, v, g, lr=0.1, beta1=0.9, beta2=0.999,
                               eps=1e-8, weight_decay=0.0,
                               beta1_pow=0.9 ** t, beta2_pow=0.999 ** t,
                               interpret=True)
    assert float(jnp.abs(p).max()) < 1.0


def test_fused_rope_matches_model_rope():
    from paddle_tpu.incubate.nn.functional import (
        fused_rotary_position_embedding)
    from paddle_tpu.models.llama import _rope_cos_sin

    rng = np.random.RandomState(0)
    b, s, h, d = 2, 16, 4, 32
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    k = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    cos, sin = _rope_cos_sin(s, d, 10000.0, jnp.float32)
    qo, ko, vo = fused_rotary_position_embedding(
        q, k, None, sin=paddle.to_tensor(np.asarray(sin)),
        cos=paddle.to_tensor(np.asarray(cos)))
    assert vo is None
    from paddle_tpu.models.llama import apply_rotary_pos_emb
    ref_q = apply_rotary_pos_emb(q._data, cos, sin)
    np.testing.assert_allclose(np.asarray(qo._data), np.asarray(ref_q),
                               rtol=1e-5, atol=1e-6)
    # rotation preserves norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qo._data), axis=-1),
        np.linalg.norm(np.asarray(q._data), axis=-1), rtol=1e-4)


def test_fused_rope_default_tables_and_position_ids():
    from paddle_tpu.incubate.nn.functional import (
        fused_rotary_position_embedding)
    from paddle_tpu.models.llama import _rope_cos_sin, apply_rotary_pos_emb

    rng = np.random.RandomState(5)
    b, s, h, d = 3, 8, 2, 16
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    # no sin/cos: default tables computed internally
    qo, _, _ = fused_rotary_position_embedding(q)
    cos, sin = _rope_cos_sin(s, d, 10000.0, jnp.float32)
    np.testing.assert_allclose(np.asarray(qo._data),
                               np.asarray(apply_rotary_pos_emb(
                                   q._data, cos, sin)),
                               rtol=1e-5, atol=1e-6)
    # batched [B, S] position_ids: reversed positions for one row
    pid = np.tile(np.arange(s), (b, 1))
    pid[1] = pid[1][::-1]
    qp, _, _ = fused_rotary_position_embedding(q, position_ids=pid)
    # row 0 matches normal rope; row 1 matches rope with reversed tables
    np.testing.assert_allclose(np.asarray(qp._data)[0],
                               np.asarray(qo._data)[0], rtol=1e-5,
                               atol=1e-6)
    ref_rev = apply_rotary_pos_emb(q._data[1:2], cos[::-1], sin[::-1])
    np.testing.assert_allclose(np.asarray(qp._data)[1],
                               np.asarray(ref_rev)[0], rtol=1e-5, atol=1e-6)


def test_fused_rope_decode_step_position_ids():
    # kv-cache decode: q of length 1, position beyond the local seq_len
    from paddle_tpu.incubate.nn.functional import (
        fused_rotary_position_embedding)
    from paddle_tpu.models.llama import _rope_cos_sin, apply_rotary_pos_emb

    rng = np.random.RandomState(7)
    q = paddle.to_tensor(rng.randn(1, 1, 2, 16).astype(np.float32))
    qo, _, _ = fused_rotary_position_embedding(
        q, position_ids=np.array([[17]]))
    cos, sin = _rope_cos_sin(18, 16, 10000.0, jnp.float32)
    ref = apply_rotary_pos_emb(q._data, cos[17:18], sin[17:18])
    np.testing.assert_allclose(np.asarray(qo._data), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # and NOT equal to position 0's rotation (the old clamping bug)
    ref0 = apply_rotary_pos_emb(q._data, cos[0:1], sin[0:1])
    assert not np.allclose(np.asarray(qo._data), np.asarray(ref0))


def test_fused_rope_half_style():
    from paddle_tpu.incubate.nn.functional import (
        fused_rotary_position_embedding)
    from paddle_tpu.models.llama import _rope_cos_sin

    rng = np.random.RandomState(6)
    b, s, h, d = 1, 4, 1, 8
    q = paddle.to_tensor(rng.randn(b, s, h, d).astype(np.float32))
    cos, sin = _rope_cos_sin(s, d, 10000.0, jnp.float32)
    qo, _, _ = fused_rotary_position_embedding(
        q, sin=paddle.to_tensor(np.asarray(sin)),
        cos=paddle.to_tensor(np.asarray(cos)), use_neox_rotary_style=False)
    # half-rotation reference
    x = np.asarray(q._data)
    c = np.asarray(cos)[None, :, None, :]
    sn = np.asarray(sin)[None, :, None, :]
    x1, x2 = x[..., :d // 2], x[..., d // 2:]
    ref = np.concatenate([x1 * c - x2 * sn, x2 * c + x1 * sn], axis=-1)
    np.testing.assert_allclose(np.asarray(qo._data), ref, rtol=1e-5,
                               atol=1e-6)


def test_bench_composition_flash_selective_scan(monkeypatch):
    """The EXACT bench.py headline composition — Pallas flash attention
    INSIDE a jax.checkpoint(selective)-wrapped lax.scan body with a full
    TrainStep — has to trace/compile/train as one program. This runs it
    interpreted on the CPU mesh (PADDLE_TPU_FLASH_INTERPRET=1) so a
    composition break (e.g. checkpoint-over-custom_vjp-in-scan) surfaces
    before a hardware window instead of burning one."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import jit, optimizer
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config

    def losses(flash: bool):
        if flash:
            monkeypatch.setenv("PADDLE_TPU_FLASH_INTERPRET", "1")
        else:
            monkeypatch.delenv("PADDLE_TPU_FLASH_INTERPRET", raising=False)
        paddle.seed(0)
        cfg = llama_tiny_config(scan_layers=True, use_recompute=True,
                                recompute_granularity="selective")
        m = LlamaForCausalLM(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=m.parameters())
        step = jit.TrainStep(lambda i, l: m(i, labels=l)[1], opt)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 64)))
        lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 64)))
        return [float(step(ids, lbl)) for _ in range(3)]

    flash_losses = losses(True)
    dense_losses = losses(False)
    assert flash_losses[-1] < flash_losses[0]
    # flash vs dense attention are numerically close, not bit-equal
    np.testing.assert_allclose(flash_losses, dense_losses, rtol=5e-3)
