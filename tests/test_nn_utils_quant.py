"""nn.utils / nn.quant / incubate.autograd / cpp_extension.

Reference test model: test/legacy_test/test_weight_norm_hook.py,
test_spectral_norm_op, test_clip_grad_*, test/quantization weight-only
tests, test/autograd/test_autograd_functional_*.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _t(a, d="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=d))


def _np(x):
    return np.asarray(x._data)


class TestNNUtils:
    def test_weight_norm_roundtrip(self):
        lin = nn.Linear(4, 6)
        w0 = _np(lin.weight).copy()
        nn.utils.weight_norm(lin, dim=1)
        x = _t(np.random.RandomState(0).randn(2, 4))
        out_wn = _np(lin(x))
        assert hasattr(lin, "weight_g") and hasattr(lin, "weight_v")
        nn.utils.remove_weight_norm(lin)
        np.testing.assert_allclose(_np(lin(x)), out_wn, atol=1e-5)
        np.testing.assert_allclose(_np(lin.weight), w0, atol=1e-5)

    def test_spectral_norm_util(self):
        lin = nn.Linear(6, 10)
        lin.weight._set_data(lin.weight._data * 5)
        nn.utils.spectral_norm(lin, n_power_iterations=10)
        lin(_t(np.random.randn(2, 6)))
        sigma = np.linalg.svd(_np(lin.weight), compute_uv=False)[0]
        assert abs(sigma - 1.0) < 0.1

    def test_param_vector_roundtrip(self):
        lin = nn.Linear(3, 5)
        vec = nn.utils.parameters_to_vector(lin.parameters())
        assert vec.shape[0] == 3 * 5 + 5
        nn.utils.vector_to_parameters(vec * 2, lin.parameters())
        np.testing.assert_allclose(
            _np(nn.utils.parameters_to_vector(lin.parameters())),
            _np(vec) * 2, atol=1e-6)

    def test_clip_grad_utils(self):
        lin = nn.Linear(4, 4)
        loss = (lin(_t(np.ones((2, 4)))) ** 2).sum()
        loss.backward()
        total = nn.utils.clip_grad_norm_(lin.parameters(), max_norm=0.01)
        new_norm = np.sqrt(sum(
            (_np(p.grad) ** 2).sum() for p in lin.parameters()
            if p.grad is not None))
        assert new_norm <= 0.011
        nn.utils.clip_grad_value_(lin.parameters(), 1e-4)
        for p in lin.parameters():
            if p.grad is not None:
                assert np.abs(_np(p.grad)).max() <= 1e-4 + 1e-9


class TestNNQuant:
    def test_weight_quant_dequant(self):
        from paddle_tpu.nn import quant as Q
        w = _t(np.random.RandomState(0).randn(8, 16))
        qw, scale = Q.weight_quantize(w)
        assert _np(qw).dtype == np.int8
        deq = Q.weight_dequantize(qw, scale, out_dtype="float32")
        assert np.abs(_np(deq) - _np(w)).max() < 0.05

    def test_weight_only_linear(self):
        from paddle_tpu.nn import quant as Q
        rng = np.random.RandomState(1)
        w = _t(rng.randn(8, 16))
        x = _t(rng.randn(3, 8))
        qw, scale = Q.weight_quantize(w)
        out = Q.weight_only_linear(x, qw, weight_scale=scale)
        ref = _np(x) @ _np(w)
        assert np.abs(_np(out) - ref).max() / np.abs(ref).max() < 0.05

    def test_stub_identity(self):
        from paddle_tpu.nn.quant import Stub
        x = _t(np.random.randn(4))
        np.testing.assert_allclose(_np(Stub()(x)), _np(x))


class TestIncubateAutograd:
    def test_vjp_jvp(self):
        from paddle_tpu.incubate import autograd as IA
        x = _t([1.0, 2.0])
        f = lambda t: (t * t).sum()
        _, g = IA.vjp(f, x)
        np.testing.assert_allclose(_np(g), [2.0, 4.0])
        _, tangent = IA.jvp(f, x, _t([1.0, 0.0]))
        assert abs(float(_np(tangent)) - 2.0) < 1e-6

    def test_jacobian_hessian_objects(self):
        from paddle_tpu.incubate import autograd as IA
        x = _t([1.0, 2.0])
        J = IA.Jacobian(lambda t: t * 3, x)
        np.testing.assert_allclose(_np(J[:]), 3 * np.eye(2), atol=1e-6)
        H = IA.Hessian(lambda t: (t * t).sum(), x)
        np.testing.assert_allclose(_np(H[:]), 2 * np.eye(2), atol=1e-6)


class TestCppExtension:
    def test_jit_load(self, tmp_path):
        from paddle_tpu.utils import cpp_extension as CE
        src = tmp_path / "mini_ext.cc"
        src.write_text("""
#include <Python.h>
static PyObject* triple(PyObject* self, PyObject* args) {
  long a; if (!PyArg_ParseTuple(args, "l", &a)) return NULL;
  return PyLong_FromLong(3 * a);
}
static PyMethodDef M[] = {{"triple", triple, METH_VARARGS, ""},
                          {NULL, NULL, 0, NULL}};
static struct PyModuleDef mod = {PyModuleDef_HEAD_INIT, "mini_ext",
                                 NULL, -1, M};
PyMODINIT_FUNC PyInit_mini_ext(void) { return PyModule_Create(&mod); }
""")
        ext = CE.load("mini_ext", [str(src)],
                      build_directory=str(tmp_path))
        assert ext.triple(7) == 21


class TestReviewRegressions2:
    def test_weight_norm_trains(self):
        lin = nn.Linear(4, 3)
        nn.utils.weight_norm(lin, dim=1)
        x = _t(np.random.RandomState(0).randn(2, 4))
        loss = (lin(x) ** 2).sum()
        loss.backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None

    def test_weight_norm_dim_handling(self):
        lin = nn.Linear(4, 6)
        nn.utils.weight_norm(lin, dim=-2)
        assert list(lin.weight_g.shape) == [4, 1]      # per-row
        lin2 = nn.Linear(4, 6)
        nn.utils.weight_norm(lin2, dim=None)
        assert list(lin2.weight_g.shape) == [1, 1]     # whole-tensor norm

    def test_spectral_norm_reads_live_weight(self):
        lin = nn.Linear(4, 3)
        nn.utils.spectral_norm(lin)
        x = _t(np.random.RandomState(0).randn(2, 4))
        o1 = _np(lin(x))
        lin.weight_orig._set_data(lin.weight_orig._data * 0 + 1.0)
        assert not np.allclose(o1, _np(lin(x)))
        loss = (lin(x) ** 2).sum()
        loss.backward()
        assert lin.weight_orig.grad is not None

    def test_vjp_multi_output(self):
        from paddle_tpu.incubate import autograd as IA
        x = _t([1.0, 2.0])
        outs, g = IA.vjp(lambda t: (t.sum(), (t * t).sum()), x)
        assert len(outs) == 2
        np.testing.assert_allclose(_np(g), [3.0, 5.0])

    def test_localfs_mv_anticlobber(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import LocalFS
        fs = LocalFS()
        a = tmp_path / "a"
        b = tmp_path / "b"
        a.write_text("a")
        b.write_text("b")
        with pytest.raises(FileExistsError):
            fs.mv(str(a), str(b))
        fs.mv(str(a), str(b), overwrite=True)
        assert b.read_text() == "a"

    def test_cpp_extension_error_shows_stderr(self, tmp_path):
        from paddle_tpu.utils import cpp_extension as CE
        bad = tmp_path / "bad.cc"
        bad.write_text("this is not C++;")
        with pytest.raises(RuntimeError) as e:
            CE.load("bad_ext", [str(bad)], build_directory=str(tmp_path))
        assert "error" in str(e.value).lower()


class TestReviewRegressions3:
    def test_hessian_multi_input_full_matrix(self):
        from paddle_tpu.incubate import autograd as IA
        x = _t([1.0, 2.0])
        y = _t([3.0])
        H = IA.Hessian(lambda a, b: (a * a).sum() + (b * b * b).sum(),
                       [x, y])
        assert H.shape == [3, 3]
        ref = np.diag([2.0, 2.0, 6.0 * 3.0])
        np.testing.assert_allclose(_np(H[:]), ref, atol=1e-5)

    def test_vjp_list_output_with_v(self):
        from paddle_tpu.incubate import autograd as IA
        x = _t([1.0, 2.0])
        outs, g = IA.vjp(lambda t: [t.sum(), (t * t).sum()], x,
                         v=[_t(1.0), _t(1.0)])
        np.testing.assert_allclose(_np(g), [3.0, 5.0])

    def test_build_dir_is_per_user(self):
        import os
        from paddle_tpu.utils import cpp_extension as CE
        d = CE.get_build_directory()
        assert str(os.getuid()) in d or "PADDLE_EXTENSION_DIR" in os.environ

    def test_spectral_norm_dim_default_linear(self):
        lin = nn.Linear(4, 6)
        nn.utils.spectral_norm(lin)   # Linear -> dim 1 (output channels)
        assert lin._spectral_norm_mod.axis == 1
