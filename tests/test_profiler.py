"""Profiler tests.

Reference coverage model: test/legacy_test/test_profiler*.py and the
profiler_statistic unit tests (SURVEY.md §5).
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 RecordEvent, export_chrome_tracing,
                                 make_scheduler)


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED       # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED       # repeat exhausted


def test_record_event_noop_when_closed():
    ev = RecordEvent("idle")
    ev.begin()
    ev.end()  # no profiler active: nothing recorded, no error


def test_profiler_records_ops_and_exports(tmp_path):
    with Profiler(targets=[ProfilerTarget.CPU]) as prof:
        x = paddle.randn([8, 8])
        y = paddle.matmul(x, x)
        with RecordEvent("user_block"):
            (y + 1).sum()
    names = {e.name for e in prof.events}
    assert "matmul" in names
    assert "user_block" in names

    path = prof.export(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    assert any(e["name"] == "matmul" for e in data["traceEvents"])

    table = prof.summary()
    assert "matmul" in table and "Calls" in table


def test_profiler_step_scheduler_windows(tmp_path):
    flushed = []

    def handler(prof):
        flushed.append(len(prof.events))

    prof = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1,
                                             repeat=2),
                    on_trace_ready=handler)
    prof.start()
    for _ in range(4):
        paddle.ones([2]).sum()
        prof.step()
    prof.stop()
    assert len(flushed) >= 1


def test_export_chrome_tracing_handler(tmp_path):
    with Profiler(on_trace_ready=export_chrome_tracing(str(tmp_path))) as p:
        paddle.ones([2]) + 1
    files = list(tmp_path.glob("*.paddle_trace.json"))
    assert len(files) == 1


def test_ops_not_recorded_when_profiler_off():
    before = len(profiler._ACTIVE)
    paddle.ones([2]) + 1
    assert len(profiler._ACTIVE) == before == 0


def test_analyze_xplane_summarizes_capture(tmp_path):
    """tools/analyze_xplane.py (VERDICT r3 weak #7): an xplane capture
    becomes quotable numbers — busy/span/duty/bubble + top ops."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cap = tmp_path / "cap"

    # capture in a FRESH process: earlier tests in this file drive the
    # Profiler's own jax.profiler sessions, after which a same-process
    # trace comes back without device event lines
    gen = subprocess.run(
        [sys.executable, "-c", (
            "import jax, jax.numpy as jnp\n"
            "f = jax.jit(lambda x: jnp.tanh(x @ x).sum())\n"
            "x = jnp.ones((256, 256)); f(x)\n"
            f"with jax.profiler.trace({str(cap)!r}):\n"
            "    f(x).block_until_ready()\n")],
        capture_output=True, text=True, timeout=120, cwd=repo)
    assert gen.returncode == 0, gen.stderr

    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "analyze_xplane.py"),
         str(cap)],
        capture_output=True, text=True, timeout=120, cwd=repo)
    assert out.returncode == 0, out.stderr
    assert "duty" in out.stdout and "dot_general" in out.stdout, out.stdout
