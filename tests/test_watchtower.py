"""Perf watchtower: request tracing, roofline attribution, SLO burn
alerts, and the bench-trajectory gate.

The acceptance bars:
  * one gateway request's trace decomposes into >= 4 nested spans
    (queue -> admit -> prefill -> decode/stream) sharing ONE trace_id,
    exportable as Chrome trace JSON;
  * a chaos-killed replica's requeued request keeps the ORIGINAL
    trace_id and every post-failover span carries ``requeued=1``;
  * ``roofline.mfu_gap`` = ceiling - observed after jit train steps;
  * multi-window burn-rate alerts fire on a sustained SLO breach and
    stay quiet on a blip (fast window only);
  * ``tools/bench_guard.py --check`` passes the committed history and
    fails a synthetic 20% tokens/s regression.

Everything runs on the CPU proxy in well under the 10s obs budget.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.gateway import Gateway
from paddle_tpu.inference.serving import ContinuousBatcher
from paddle_tpu.observability import (SLO, BurnWindow, SLOMonitor,
                                      TraceContext, get_recorder,
                                      new_trace)
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.observability import roofline_attr
from paddle_tpu.resilience import arm_scenario, disarm

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    disarm()
    yield
    disarm()


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _batcher(lm, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("s_max", 64)
    return ContinuousBatcher(lm, compile=False, **kw)


def _prompts(seed, sizes):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 128, size=n).astype(np.int64) for n in sizes]


# -- trace context unit pieces ------------------------------------------------

def test_trace_context_ids_baggage_and_traceparent_roundtrip():
    ctx = new_trace("request", gid=7)
    assert ctx.root is not None and ctx.root.open
    sp = ctx.begin("phase_a", hint="x")
    assert sp.trace_id == ctx.trace_id
    assert sp.parent_id == ctx.root.span_id
    ctx.baggage["requeued"] = 1
    late = ctx.begin("phase_b")
    assert late.tags["requeued"] == 1        # baggage merges at begin
    assert "requeued" not in sp.tags         # ...not retroactively
    sp.end()
    assert not sp.open and sp.duration_s >= 0
    sp.end(extra=1)                          # idempotent: tags merge only
    assert sp.tags["extra"] == 1
    late.end()
    ctx.finish(ok=1)

    hdr = ctx.traceparent()
    back = TraceContext.from_traceparent(hdr, ctx.baggage_header())
    assert back.trace_id == ctx.trace_id
    assert back.baggage["requeued"] == "1"
    with pytest.raises(ValueError):
        TraceContext.from_traceparent("garbage")


def test_chrome_export_structure():
    rec = get_recorder()
    ctx = new_trace("request")
    ctx.begin("inner").end()
    ctx.finish()
    doc = rec.to_chrome(ctx.trace_id)
    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in events} == {"request", "inner"}
    for e in events:
        assert e["args"]["trace_id"] == ctx.trace_id
        assert e["ts"] >= 0 and e["dur"] >= 0
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(ctx.trace_id in m["args"]["name"] for m in metas)


# -- gateway trace decomposition ----------------------------------------------

def test_gateway_request_trace_decomposes_ttft(lm, tmp_path):
    gw = Gateway()
    gw.add_replica("r0", _batcher(lm))
    prompt = _prompts(1, (6,))[0]
    sess = gw.stream(prompt, 6)
    toks = list(sess)
    assert len(toks) == 6
    rec = get_recorder()
    tid = rec.trace_ids()[-1]
    spans = rec.spans(tid)
    names = {s.name for s in spans}
    # the acceptance bar: >= 4 nested spans, one trace_id
    assert {"queue", "admit", "prefill", "decode", "stream"} <= names
    assert all(s.trace_id == tid for s in spans)
    by_name = {s.name: s for s in spans}
    root = by_name["gateway.request"]
    assert by_name["queue"].parent_id == root.span_id
    assert by_name["prefill"].parent_id == by_name["admit"].span_id
    assert by_name["decode"].tags["tokens"] == 6
    # exports round-trip
    p = rec.export_chrome(str(tmp_path / "trace.json"), tid)
    doc = json.load(open(p))
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) >= 5
    jl = rec.export_jsonl(str(tmp_path / "trace.jsonl"), tid)
    lines = [json.loads(l) for l in open(jl)]
    assert {l["name"] for l in lines} == names


def test_trace_survives_chaos_failover_with_requeued_tag(lm):
    """A replica dies mid-decode; the resumed request keeps its original
    trace_id, records a ``requeue`` marker, and every span begun after
    the failover carries ``requeued=1``."""
    prompts = _prompts(6, (5, 9, 7, 11))
    gw = Gateway(policy="least_loaded")
    gw.add_replica("r0", _batcher(lm))
    gw.add_replica("r1", _batcher(lm))
    gids = [gw.submit(p, 10) for p in prompts]
    traces = {g: gw._requests[g].trace.trace_id for g in gids}
    arm_scenario("seed=0; serving.step:transient_error:after=6,count=3")
    for _ in range(1000):
        if not gw._has_work():
            break
        gw.step()
    assert gw.stats()["requeued"] > 0
    assert gw.stats()["completions"] == 4
    rec = get_recorder()
    hit = 0
    for g, tid in traces.items():
        spans = rec.spans(tid)
        assert spans and all(s.trace_id == tid for s in spans)
        if not any(s.name == "requeue" for s in spans):
            continue
        hit += 1
        post = [s for s in spans
                if s.name in ("queue", "admit", "prefill", "decode")
                and s.tags.get("requeued") == 1]
        # the failed attempt's interrupted spans closed; the resumed
        # attempt re-ran the whole pipeline under the requeued tag
        assert {"queue", "admit", "prefill", "decode"} \
            <= {s.name for s in post}
        assert any(s.tags.get("interrupted") == 1 for s in spans)
    assert hit > 0, "no requeued request left a trace"


# -- roofline attribution -----------------------------------------------------

def test_roofline_mfu_gap_after_jit_train_steps():
    from paddle_tpu import hapi, nn, optimizer
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    m = hapi.Model(net)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.01,
                                      parameters=net.parameters()),
              loss=nn.CrossEntropyLoss(), jit=True)
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (8, 1)).astype(np.int64)
    for _ in range(3):
        m.train_batch([x], [y])
    reg = get_registry()
    observed = reg.get("roofline.observed_mfu").value
    ceiling = reg.get("roofline.mfu_ceiling").value
    gap = reg.get("roofline.mfu_gap").value
    assert observed == reg.get("train_mfu").value
    assert gap == pytest.approx(ceiling - observed, abs=1e-9)
    assert 0.0 < ceiling <= 1.0
    # attribution fractions are a partition of the observed step
    attr = reg.get("roofline.gap_attribution")
    fracs = {ch.labels["phase"]: ch.value for ch in attr.children()}
    assert set(fracs) == {"compute", "memory", "overhead"}
    assert all(0.0 <= v <= 1.0 for v in fracs.values())
    # warm jit steps also feed the steady-state histogram
    assert reg.get("train.fused_step_seconds").count >= 1


def test_roofline_attribution_arithmetic(tmp_path, monkeypatch):
    model = {"configs": [
        {"config": "toy", "params": 1000, "batch": 1, "seq": 100,
         "t_compute_ms": 40.0, "t_memory_ms": 60.0, "bound": "memory",
         "tokens_per_s_bound": 1000.0, "measured_mfu_ceiling": 0.6},
    ]}
    p = tmp_path / "ROOFLINE.json"
    p.write_text(json.dumps(model))
    monkeypatch.setenv("PADDLE_ROOFLINE", str(p))
    roofline_attr.clear_cache()
    try:
        # 100 tokens (scale 1): compute 40ms, memory 60ms -> ideal 60ms;
        # observed 120ms: compute 1/3, exposed memory (60-40)/120 = 1/6,
        # overhead (120-60)/120 = 1/2
        out = roofline_attr.observe_train_step(0.120, observed_mfu=0.2,
                                               tokens=100)
        assert out["mfu_gap"] == pytest.approx(0.4)
        assert out["bound"] == "memory"
        assert out["compute_frac"] == pytest.approx(1 / 3)
        assert out["memory_frac"] == pytest.approx(1 / 6)
        assert out["overhead_frac"] == pytest.approx(1 / 2)
        # serving join: 500 tok/s observed vs 1000 bound -> 0.5
        roofline_attr.observe_serving_step(0.1, tokens=50)
        reg = get_registry()
        assert reg.get("roofline.serving.bound_frac").value \
            == pytest.approx(0.5)
    finally:
        roofline_attr.clear_cache()


def test_roofline_missing_file_is_silent(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_ROOFLINE",
                       str(tmp_path / "nope.json"))
    roofline_attr.clear_cache()
    try:
        assert roofline_attr.observe_train_step(0.1, 0.5) is None
        roofline_attr.observe_serving_step(0.1, 10)   # no raise
    finally:
        roofline_attr.clear_cache()


# -- SLO burn-rate alerts -----------------------------------------------------

def _slo_rig():
    """Fresh registry histogram + monitor on a fake clock."""
    reg = get_registry()
    name = f"watchtower.test_latency_{os.getpid()}_{id(object())}"
    h = reg.histogram(name, "x")
    clock = [0.0]
    slo = SLO("test", name, threshold_s=0.5, objective=0.9)
    win = BurnWindow(fast_s=10.0, slow_s=60.0, burn_threshold=5.0,
                     severity="page")
    mon = SLOMonitor([slo], windows=[win], registry=reg,
                     clock=lambda: clock[0])
    return h, mon, clock


def test_slo_burn_alert_fires_on_sustained_breach_only():
    h, mon, clock = _slo_rig()
    mon.poll()
    # healthy baseline INSIDE the slow window, older than the fast one
    for _ in range(100):
        h.observe(0.01)
    clock[0] = 25.0
    assert mon.poll() == []
    clock[0] = 34.0
    mon.poll()
    # a BLIP: 100% bad inside the fast window — the slow window is still
    # diluted by the baseline, so no page
    for _ in range(20):
        h.observe(5.0)
    clock[0] = 40.0
    assert mon.poll() == []
    # sustained breach: keep burning until the slow window catches up
    fired = []
    for t in range(1, 30):
        clock[0] = 40.0 + t * 5.0
        for _ in range(20):
            h.observe(5.0)
        fired = mon.poll()
        if fired:
            break
    assert fired and fired[0].slo == "test"
    assert fired[0].severity == "page"
    assert fired[0].burn_fast >= 5.0 and fired[0].burn_slow >= 5.0
    # edge-triggered: still burning -> no duplicate alert
    clock[0] += 5.0
    for _ in range(10):
        h.observe(5.0)
    assert mon.poll() == []
    assert len(mon.alerts) == 1
    summary = mon.summary()
    assert summary["slos"][0]["firing"] == ["page"]
    assert len(summary["alerts"]) == 1


def test_slo_monitor_recovers_and_rearms():
    h, mon, clock = _slo_rig()
    for _ in range(10):
        h.observe(5.0)          # 100% bad from the start
    mon.poll()
    clock[0] = 60.0
    for _ in range(10):
        h.observe(5.0)
    assert len(mon.poll()) == 1          # burning in both windows
    # long healthy stretch clears the windows -> condition re-arms
    for t in range(1, 15):
        clock[0] = 60.0 + t * 10.0
        for _ in range(200):
            h.observe(0.01)
        mon.poll()
    assert mon.summary()["slos"][0]["firing"] == []
    clock[0] += 10.0
    for _ in range(400):
        h.observe(5.0)
    clock[0] += 60.0
    for _ in range(400):
        h.observe(5.0)
    assert len(mon.poll()) == 1          # re-fired after re-arming


def test_default_gateway_slos_read_real_histograms(lm):
    from paddle_tpu.observability import default_gateway_slos
    gw = Gateway()
    gw.add_replica("r0", _batcher(lm))
    mon = SLOMonitor(default_gateway_slos(ttft_s=2.5, tpot_s=2.5))
    mon.poll()
    gids = [gw.submit(p, 4) for p in _prompts(2, (5, 6))]
    gw.run_until_done()
    mon.poll()
    s = mon.summary()
    ttft = next(x for x in s["slos"] if x["name"] == "gateway_ttft")
    assert ttft["total"] >= 2        # the histogram really was read
    assert gids


# -- bench trajectory gate ----------------------------------------------------

def _guard(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_guard.py")]
        + args, capture_output=True, text=True)


def test_bench_guard_passes_committed_history():
    r = _guard(["--check", "--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["status"] in ("pass", "no_history")
    if report["series"]:
        # the wedged r01 round is skipped, not a failure
        assert any(s["reason"].startswith("rc=")
                   for s in report["skipped"]) or not report["skipped"]


def test_bench_guard_fails_synthetic_regression(tmp_path):
    hist = [21823.39, 22649.3, 22886.63, 23086.26]
    for i, v in enumerate(hist, start=2):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(
            {"n": i, "rc": 0, "parsed": {
                "metric": "llama_train_tokens_per_sec_per_chip",
                "value": v, "unit": "tokens/s",
                "detail": {"tpu": False}}}))
    ok = _guard(["--check", "--dir", str(tmp_path)])
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # a 20% tokens/s drop must gate
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(
        {"n": 6, "rc": 0, "parsed": {
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.8 * hist[-1], "unit": "tokens/s",
            "detail": {"tpu": False}}}))
    bad = _guard(["--check", "--dir", str(tmp_path), "--json"])
    assert bad.returncode == 1
    report = json.loads(bad.stdout)
    key = "llama_train_tokens_per_sec_per_chip/cpu"
    assert report["series"][key]["status"] == "regression"
    assert report["series"][key]["drop_frac"] == pytest.approx(0.2,
                                                               abs=0.02)
    # TPU and CPU points never gate each other
    (tmp_path / "BENCH_r07.json").write_text(json.dumps(
        {"n": 7, "rc": 0, "parsed": {
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 29025.0, "unit": "tokens/s",
            "detail": {"tpu": True}}}))
    mixed = _guard(["--json", "--dir", str(tmp_path)])
    rep = json.loads(mixed.stdout)
    tpu_key = "llama_train_tokens_per_sec_per_chip/tpu"
    assert rep["series"][tpu_key]["status"] == "insufficient_history"


def test_bench_guard_multichip_lane_disjoint(tmp_path):
    """MULTICHIP_r*.json is its own lane: pre-lane dry-run wrappers
    (rounds without a parsed bench line) skip cleanly, the series gates
    independently, and train-lane history is never consulted."""
    (tmp_path / "MULTICHIP_r05.json").write_text(json.dumps(
        {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
         "tail": "dryrun_multichip(8): OK"}))
    hist = [350.0, 362.0, 371.0, 380.0]
    for i, v in enumerate(hist, start=6):
        (tmp_path / f"MULTICHIP_r{i:02d}.json").write_text(json.dumps(
            {"metric": "multichip_sharded_train_tokens_per_sec",
             "value": v, "unit": "tokens/s",
             "detail": {"tpu": False}}))
    ok = _guard(["--check", "--dir", str(tmp_path), "--json"])
    assert ok.returncode == 0, ok.stdout + ok.stderr
    report = json.loads(ok.stdout)
    key = "multichip:multichip_sharded_train_tokens_per_sec/cpu"
    assert report["series"][key]["status"] == "pass"
    assert list(report["series"]) == [key]   # no train/gateway bleed
    assert any(s["lane"] == "multichip" and s["round"] == 5
               for s in report["skipped"])
    # a 20% sharded-rate drop gates this lane like any other
    (tmp_path / "MULTICHIP_r10.json").write_text(json.dumps(
        {"metric": "multichip_sharded_train_tokens_per_sec",
         "value": 0.8 * hist[-1], "unit": "tokens/s",
         "detail": {"tpu": False}}))
    bad = _guard(["--check", "--dir", str(tmp_path), "--json"])
    assert bad.returncode == 1
    assert json.loads(bad.stdout)["series"][key]["status"] == "regression"


def test_telemetry_dump_chrome_and_slo_flags():
    """Flag plumbing only (--no-workload keeps it fast)."""
    tool = os.path.join(REPO, "tools", "telemetry_dump.py")
    r = subprocess.run(
        [sys.executable, tool, "--format", "chrome", "--no-workload"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "traceEvents" in json.loads(r.stdout)
    r = subprocess.run(
        [sys.executable, tool, "--format", "jsonl", "--no-workload",
         "--slo"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "# slo summary" in r.stdout
    # incompatible combos error out loudly
    r = subprocess.run(
        [sys.executable, tool, "--format", "chrome", "--snapshot", "x"],
        capture_output=True, text=True)
    assert r.returncode != 0
