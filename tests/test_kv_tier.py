"""Tiered radix KV cache (round 17): host-DRAM spill + async promotion.

Four layers, <60s total:

  * tier units — HostTier/DiskTier byte accounting, blob roundtrips,
    demotion state machine (device -> host -> disk -> gone), host-LRU
    overflow, the cached-summary invalidation contract, and the
    ``evictable_pages() == evict(n)`` property under interleaved
    pin/unpin (no model, sub-second);
  * transfer plumbing — AsyncLoader futures + idempotent bounded close,
    DevicePrefetcher.close() waking a feeder blocked mid-put;
  * serving integration — churn workloads (working set > device pool)
    must stay TOKEN-EXACT vs solo ``generate`` across seeds with
    demotions and promotions actually happening, pages + tier bytes
    audited to zero leak; chaos at ``kv.host_demote``/``kv.host_promote``
    must degrade to recompute/full prefill, still token-exact;
  * control plane — the router prefers device-resident prefix depth,
    the gateway failover drill stays token-exact with tiered replicas,
    and ``telemetry_dump --prefix-stats`` reports the per-tier columns.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.prefix_cache import (DiskTier, HostTier,
                                               RadixPrefixCache,
                                               blob_nbytes, chain_hashes)
from paddle_tpu.inference.serving import PagedContinuousBatcher
from paddle_tpu.resilience import arm_scenario, disarm

pytestmark = pytest.mark.kvtier

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    disarm()
    yield
    disarm()


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    return m


def _ref(lm, prompt, n):
    return np.asarray(lm.generate(prompt.reshape(1, -1),
                                  max_new_tokens=n)).reshape(-1)


def _churn_prompts(seed, n_prefixes=6, n_requests=14, prefix_len=48,
                   tail=5):
    """Churn stream: one cold pass over every shared prefix (the working
    set — n_prefixes * 3 pages at block 16 — overflows the device pool,
    so the early chains demote), then random re-references that must
    come back via promotion. Tails are unique per request."""
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(0, 128, (prefix_len,)).astype(np.int64)
                for _ in range(n_prefixes)]
    picks = (list(range(n_prefixes))
             + list(rng.randint(0, n_prefixes,
                                (max(n_requests - n_prefixes, 0),))))
    return [np.concatenate([prefixes[p], rng.randint(0, 128, (tail,))])
            for p in picks]


def _tiered(lm, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("s_max", 96)
    kw.setdefault("block_size", 16)
    kw.setdefault("n_pages", 14)
    kw.setdefault("compile", False)
    kw.setdefault("policy", "ondemand")
    kw.setdefault("prefix_cache", True)
    kw.setdefault("host_kv_gib", 0.25)
    return PagedContinuousBatcher(lm, **kw)


# -- tier units (no model) ----------------------------------------------------

def _blob(fill, shape=(2, 4)):
    return {"t": [(np.full(shape, fill, np.float32),
                   np.full(shape, fill + 1, np.float32))]}


def test_host_tier_accounting():
    t = HostTier(capacity_bytes=1 << 20)
    b = _blob(1.0)
    nb = t.put(7, b)
    assert nb == blob_nbytes(b) == t.used_bytes
    assert 7 in t and len(t) == 1 and t.stored == 1
    assert t.get(7) is b and t.nbytes_of(7) == nb
    assert t.discard(7) == nb
    assert t.used_bytes == 0 and 7 not in t


def test_disk_tier_roundtrip_and_unlink(tmp_path):
    t = DiskTier(str(tmp_path / "kv"), capacity_bytes=1 << 20)
    blob = {"t": [(np.arange(8, dtype=np.float32).reshape(2, 4),
                   np.ones((2, 4), np.float16))],
            "d": [(np.zeros((1, 2), np.float32),) * 2]}
    t.put(3, blob)
    back = t.get(3)
    assert back["t"][0][0].dtype == np.float32
    assert back["t"][0][1].dtype == np.float16
    np.testing.assert_array_equal(back["t"][0][0], blob["t"][0][0])
    np.testing.assert_array_equal(back["d"][0][1], blob["d"][0][1])
    files = os.listdir(str(tmp_path / "kv"))
    assert len(files) == 1
    t.discard(3)
    assert os.listdir(str(tmp_path / "kv")) == [] and t.used_bytes == 0


def _tiered_cache(block_size=4, host_cap=1 << 20, disk=None):
    tier = HostTier(host_cap, next_tier=disk)
    c = RadixPrefixCache(block_size, host_tier=tier,
                         spill=lambda node: _blob(float(node.page)))
    return c, tier


def test_demote_keeps_chain_matchable_and_splits():
    c, tier = _tiered_cache()
    toks = np.arange(12)                                   # 3 blocks
    created = c.insert(toks, pages=[5, 6, 7], start_block=0, n_blocks=3)
    c.unpin(created)
    freed = c.evict(2)                                     # deepest first
    assert freed == [7, 6]
    assert c.cached_pages == 1 and len(c) == 3             # nodes survive
    path = c.match(toks)
    assert len(path) == 3
    dev, hosted = RadixPrefixCache.split_device(path)
    assert [n.page for n in dev] == [5]
    assert [n.residency for n in hosted] == ["host", "host"]
    assert all(n.page == -1 for n in hosted)
    rep = c.audit_tiers()
    assert rep["host_nodes"] == 2 and rep["host_bytes"] == tier.used_bytes
    # promotion flips them back and drops the blobs
    c.promote_node(hosted[0], page=8, nbytes=64)
    c.promote_node(hosted[1], page=9, nbytes=64)
    assert c.cached_pages == 3 and c.audit_tiers()["host_nodes"] == 0
    assert c.promotions == 2 and c.promoted_bytes == 128


def test_demote_failure_drops_subtree_cleanly():
    tier = HostTier(1 << 20)
    calls = {"n": 0}

    def spill(node):
        calls["n"] += 1
        raise RuntimeError("pool read failed")

    c = RadixPrefixCache(4, host_tier=tier, spill=spill)
    created = c.insert(np.arange(8), [1, 2], 0, 2)
    c.unpin(created)
    freed = c.evict(2)
    assert freed == [2, 1] and calls["n"] == 2
    assert len(c) == 0 and c.cached_pages == 0
    assert c.demote_failures == 2 and c.demotions == 0
    assert c.audit_tiers() == {"host_bytes": 0, "host_nodes": 0}


def test_host_lru_overflow_spills_to_disk(tmp_path):
    one = blob_nbytes(_blob(0.0))
    disk = DiskTier(str(tmp_path / "kv"), capacity_bytes=1 << 20)
    c, tier = _tiered_cache(host_cap=2 * one, disk=disk)
    # three independent single-block chains demoted through a 2-blob host
    chains = [np.array([i, i, i, i]) for i in range(3)]
    for i, toks in enumerate(chains):
        created = c.insert(toks, [10 + i], 0, 1)
        c.unpin(created)
        c.evict(1)
    assert c.demotions == 3
    rep = c.audit_tiers()
    assert rep["host_nodes"] == 2 and rep["disk_nodes"] == 1
    assert tier.evicted == 1                  # host LRU pushed down-chain
    # the disk-resident node (first demoted = LRU victim) still matches
    # and its blob reads back through the same interface
    path = c.match(chains[0])
    assert len(path) == 1 and path[0].residency == "disk"
    assert blob_nbytes(c.node_blob(path[0])) == one


def test_host_overflow_without_disk_drops():
    one = blob_nbytes(_blob(0.0))
    c, tier = _tiered_cache(host_cap=one)     # room for exactly one blob
    for i in range(2):
        created = c.insert(np.array([i] * 4), [20 + i], 0, 1)
        c.unpin(created)
        c.evict(1)
    assert len(c) == 1 and tier.evicted == 1  # first chain is gone
    assert c.match(np.array([0] * 4)) == []
    assert c.match(np.array([1] * 4))[0].residency == "host"
    c.audit_tiers()


def test_summary_cached_and_invalidated_on_every_transition():
    c, _ = _tiered_cache()
    created = c.insert(np.arange(8), [1, 2], 0, 2)
    s1 = c.summary()
    assert c.summary() is s1                   # cached between mutations
    h1, h2 = chain_hashes(np.arange(8), 4)
    assert s1["tiers"] == {h1: "device", h2: "device"}
    c.unpin(created)
    c.evict(1)                                 # demotion invalidates
    s2 = c.summary()
    assert s2 is not s1 and s2["tiers"][h2] == "host"
    node = c.match(np.arange(8))[1]
    c.promote_node(node, page=3)               # promotion invalidates
    s3 = c.summary()
    assert s3 is not s2 and s3["tiers"][h2] == "device"
    # untiered eviction (drop) removes the hash entirely
    u = RadixPrefixCache(4)
    cr = u.insert(np.arange(8), [1, 2], 0, 2)
    u.unpin(cr)
    s4 = u.summary()
    u.evict(2)
    s5 = u.summary()
    assert s5 is not s4 and s5["hashes"] == {}


def test_evictable_pages_equals_evict_under_pin_churn():
    """Satellite property: the capacity planner (evictable_pages) and
    the executor (evict) agree EXACTLY at every point of an interleaved
    insert/pin/unpin/evict history — tiered and untiered."""
    for tiered in (False, True):
        if tiered:
            c, _ = _tiered_cache(block_size=2, host_cap=1 << 20)
        else:
            c = RadixPrefixCache(2)
        rng = np.random.RandomState(7 + tiered)
        next_page = [0]
        pinned = []                            # (nodes) we must release

        def fresh_pages(n):
            out = list(range(next_page[0], next_page[0] + n))
            next_page[0] += n
            return out

        for step in range(60):
            op = rng.randint(4)
            if op == 0:                        # insert a random chain
                blocks = rng.randint(1, 4)
                toks = rng.randint(0, 4, (blocks * 2,))
                created = c.insert(toks, fresh_pages(blocks), 0, blocks)
                if created and rng.randint(2):
                    c.unpin(created)
                elif created:
                    pinned.append(created)
            elif op == 1 and pinned:           # release an old pin
                c.unpin(pinned.pop(rng.randint(len(pinned))))
            elif op == 2:                      # pin a matched path
                toks = rng.randint(0, 4, (rng.randint(1, 4) * 2,))
                path = c.match(toks)
                if path:
                    c.pin(path)
                    pinned.append(path)
            else:                              # the property checkpoint
                want = c.evictable_pages()
                freed = c.evict(want + 7)      # ask for MORE than exists
                assert len(freed) == want, (tiered, step)
        for nodes in pinned:
            c.unpin(nodes)
        assert c.evictable_pages() == len(c.evict(10 ** 6))
        assert c.cached_pages == 0


# -- transfer plumbing --------------------------------------------------------

def test_async_loader_future_and_idempotent_close():
    from paddle_tpu.perf.prefetch import AsyncLoader
    ld = AsyncLoader(depth=2)
    payload = [np.arange(6, dtype=np.float32), np.ones((2, 2))]
    fut = ld.submit(payload)
    out = fut.result(timeout=10.0)
    assert fut.done()
    np.testing.assert_array_equal(np.asarray(out[0]), payload[0])
    ld.close()
    ld.close()                                 # second close is a no-op
    assert not any(t.is_alive() for t in ld._threads)
    with pytest.raises(RuntimeError):
        ld.submit(payload)


def test_device_prefetcher_close_wakes_blocked_feeder():
    from paddle_tpu.perf.prefetch import DevicePrefetcher

    def endless():
        i = 0
        while True:
            yield np.full((2,), i, np.float32)
            i += 1

    p = DevicePrefetcher(endless(), depth=1, transfer=lambda b: b)
    first = next(p)                            # feeder now blocks on put
    assert first is not None
    p.close(timeout=5.0)
    assert p._retired and not p._thread.is_alive()
    p.close(timeout=5.0)                       # idempotent
    with pytest.raises(StopIteration):
        next(p)


# -- serving integration ------------------------------------------------------

def test_tiered_churn_token_exact_across_seeds(lm):
    """Working set (6 prefixes x 3 blocks = 18 pages) over a 14-page
    pool: demotion + promotion must both fire and every output must
    equal solo generate. Zero leaked pages, zero leaked host bytes."""
    for seed in (3, 11):
        prompts = _churn_prompts(seed)
        refs = [_ref(lm, p, 4) for p in prompts]
        bt = _tiered(lm)
        try:
            rids = [bt.submit(p, 4) for p in prompts]
            outs = bt.run_until_done(max_steps=20000)
            for rid, ref in zip(rids, refs):
                np.testing.assert_array_equal(outs[rid], ref)
            st = bt.prefix_cache.stats()
            assert st["demotions"] > 0, seed
            assert st["promotions"] > 0, seed
            assert st["host_hit_tokens"] > 0, seed
            bt.audit_pages()                   # device cover + tier bytes
            assert bt._promo is None
        finally:
            bt.close()


def test_promotion_chaos_degrades_to_full_prefill(lm):
    """kv.host_promote fault on EVERY attempt: admission must fall back
    to full prefill (token-exact), count the failures, promote nothing,
    and leave pages + tiers clean."""
    prompts = _churn_prompts(5, n_requests=10)
    refs = [_ref(lm, p, 4) for p in prompts]
    bt = _tiered(lm)
    try:
        arm_scenario("seed=0; kv.host_promote:transient_error:count=999")
        rids = [bt.submit(p, 4) for p in prompts]
        outs = bt.run_until_done(max_steps=20000)
        for rid, ref in zip(rids, refs):
            np.testing.assert_array_equal(outs[rid], ref)
        st = bt.prefix_cache.stats()
        assert st["promotion_failures"] > 0
        assert st["promotions"] == 0
        assert st["demotions"] > 0             # spill itself kept working
        bt.audit_pages()
    finally:
        bt.close()


def test_demotion_chaos_drops_chains_cleanly(lm):
    """kv.host_demote faults on half the spills: failed demotions drop
    the chain (recompute next time) instead of leaking pages or bytes;
    outputs stay token-exact."""
    prompts = _churn_prompts(9, n_requests=10)
    refs = [_ref(lm, p, 4) for p in prompts]
    bt = _tiered(lm)
    try:
        arm_scenario("seed=0; kv.host_demote:transient_error:p=0.5")
        rids = [bt.submit(p, 4) for p in prompts]
        outs = bt.run_until_done(max_steps=20000)
        for rid, ref in zip(rids, refs):
            np.testing.assert_array_equal(outs[rid], ref)
        st = bt.prefix_cache.stats()
        assert st["demote_failures"] > 0
        bt.audit_pages()
    finally:
        bt.close()


def test_promotion_latency_histogram_populates(lm):
    from paddle_tpu.observability.metrics import get_registry
    h = get_registry().histogram("serving.prefix_promotion_seconds")
    before = h.count
    prompts = _churn_prompts(13, n_requests=10)
    bt = _tiered(lm)
    try:
        for p in prompts:
            bt.submit(p, 4)
        bt.run_until_done(max_steps=20000)
        assert h.count > before
        assert h.quantile(0.99) is not None
    finally:
        bt.close()


# -- control plane ------------------------------------------------------------

class _FakeReplica:
    def __init__(self, name, summary, load=0):
        self.name = name
        self.load = load
        self.weight = 1.0
        self.warm_buckets = set()
        self._summary = summary

    def prefix_summary(self):
        return self._summary


class _FakeReq:
    session_id = None
    bucket = None

    def __init__(self, prompt):
        self.prompt = prompt


def test_router_prefers_device_resident_depth():
    from paddle_tpu.inference.gateway.router import SessionAffinityPolicy
    prompt = np.arange(8)
    h1, h2 = chain_hashes(prompt, 4)
    hashes = {h1: 1, h2: 2}
    all_dev = _FakeReplica("dev", {
        "block_size": 4, "hashes": hashes,
        "tiers": {h1: "device", h2: "device"}}, load=5)
    tail_host = _FakeReplica("hosty", {
        "block_size": 4, "hashes": hashes,
        "tiers": {h1: "device", h2: "host"}}, load=0)
    pol = SessionAffinityPolicy()
    # equal total depth: device-resident depth wins even at higher load
    assert pol.select(_FakeReq(prompt),
                      [tail_host, all_dev]) is all_dev
    # but total depth still dominates: a full host chain beats a
    # shallower device chain (promotion is a memcpy, prefill is flops)
    shallow_dev = _FakeReplica("shallow", {
        "block_size": 4, "hashes": {h1: 1}, "tiers": {h1: "device"}})
    full_host = _FakeReplica("deep", {
        "block_size": 4, "hashes": hashes,
        "tiers": {h1: "host", h2: "host"}})
    assert pol.select(_FakeReq(prompt),
                      [shallow_dev, full_host]) is full_host
    # pre-tier summaries (no "tiers" key) count as all-device
    legacy = _FakeReplica("legacy", {"block_size": 4, "hashes": hashes})
    assert pol.select(_FakeReq(prompt),
                      [tail_host, legacy]) is legacy


def test_gateway_failover_with_tiered_replicas_token_exact(lm):
    """The round-13 failover drill with host tiers armed: a chaos-killed
    tiered replica's requests requeue and finish token-exact; the
    survivor's pages AND tier bytes audit clean."""
    from paddle_tpu.inference.gateway import Gateway
    rng = np.random.RandomState(21)
    shared = rng.randint(0, 128, (32,)).astype(np.int64)
    prompts = [np.concatenate(
        [shared, rng.randint(0, 128, (n,)).astype(np.int64)])
        for n in (5, 7, 6, 9)]
    refs = [_ref(lm, p, 8) for p in prompts]
    gw = Gateway(policy="affinity")
    gw.add_replica("r0", _tiered(lm, n_pages=16))
    gw.add_replica("r1", _tiered(lm, n_pages=16))
    gids = [gw.submit(p, 8) for p in prompts]
    arm_scenario("seed=0; serving.step:transient_error:after=6,count=3")
    dead = None
    for _ in range(2000):
        gw.step()
        dead = next((r for r in gw.pool.replicas() if not r.alive), None)
        if dead is not None:
            break
    assert dead is not None, "chaos never killed a replica"
    for _ in range(4000):
        if not gw._has_work():
            break
        gw.step()
    s = gw.stats()
    assert s["requeued"] > 0 and s["failures"] == 0
    for g, ref in zip(gids, refs):
        np.testing.assert_array_equal(gw.pop_result(g), ref)
    for r in gw.pool.replicas():
        if r.alive:
            r.batcher.audit_pages()
            r.batcher.close()


def test_telemetry_dump_prefix_stats_reports_tier_columns(
        tmp_path, monkeypatch, capsys):
    from paddle_tpu.observability import fleet
    from paddle_tpu.observability.metrics import get_registry
    reg = get_registry()
    reg.counter("serving.prefix_hit_tokens", "t").inc(80)
    reg.counter("serving.prefix_miss_tokens", "t").inc(20)
    tier_c = reg.counter("serving.prefix_tier_hit_tokens", "t",
                         labelnames=("tier",))
    tier_c.labels(tier="device").inc(48)
    tier_c.labels(tier="host").inc(32)
    reg.counter("serving.prefix_promotions", "t").inc(2)
    reg.counter("serving.prefix_demoted_bytes", "t").inc(4096)
    reg.histogram("serving.prefix_promotion_seconds", "t").observe(0.02)
    monkeypatch.setenv("PADDLE_TELEMETRY_DIR", str(tmp_path))
    fleet.reset_spool()
    try:
        fleet.spool_metrics()
    finally:
        fleet.reset_spool()
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "telemetry_dump", os.path.join(REPO, "tools",
                                       "telemetry_dump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(["--fleet", str(tmp_path), "--prefix-stats"])
    assert rc == 0
    out = capsys.readouterr().out
    line = next(ln for ln in out.splitlines()
                if ln.startswith("# fleet prefix-stats "))
    stats = json.loads(line[len("# fleet prefix-stats "):])
    # >= because the process-global registry may carry traffic from the
    # serving tests above — the columns just have to be present and sane
    assert stats["hit_tokens_by_tier"]["host"] >= 32
    assert stats["hit_tokens_by_tier"]["device"] >= 48
    assert stats["promotions"] >= 2
    assert stats["demoted_bytes"] >= 4096
    assert stats["promotion_latency_p50_ms"] is not None
    assert stats["promotion_latency_p99_ms"] is not None
