// paddle_tpu native runtime tier (C++).
//
// TPU-native equivalents of the reference's native runtime components
// (SURVEY.md §2.1):
//   - TCPStore        — phi/core/distributed/store/tcp_store.h:121 analog:
//                       rank-0 TCP key/value server with blocking get/wait,
//                       atomic add, used for multi-host bootstrap, barriers,
//                       and elastic membership (control plane over DCN).
//   - BlockingQueue   — fluid/imperative/data_loader.cc blocking-queue analog:
//                       bounded producer/consumer queue that releases the GIL
//                       while waiting (dataloader prefetch, pipeline p2p).
//   - HostTracer      — platform/profiler/host_tracer.cc analog: nanosecond
//                       RecordEvent spans with thread ids, drained to Python
//                       for chrome-trace export.
//   - Stat registry   — fluid/memory/stats.h DEVICE_MEMORY_STAT analog:
//                       named current/peak counters.
//
// Exposed as flat functions + integer handles; the Python-facing classes live
// in paddle_tpu/core/native.py. Built with plain g++ (no pybind11 in image).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

static int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// wire helpers (length-prefixed protocol, all little-endian on x86)
// ---------------------------------------------------------------------------

enum Cmd : uint8_t {
  kSet = 1,
  kGet = 2,   // blocking until key exists or client timeout
  kAdd = 3,   // atomic add, creates key at 0
  kCheck = 4, // non-blocking existence check
  kDel = 5,
  kList = 6,  // list keys with a prefix
};

enum Status : uint8_t { kOk = 0, kTimeout = 1, kMissing = 2, kError = 3 };

static bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

static bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

static bool send_str(int fd, const std::string& s) {
  uint32_t len = static_cast<uint32_t>(s.size());
  return send_all(fd, &len, 4) && (len == 0 || send_all(fd, s.data(), len));
}

static bool recv_str(int fd, std::string* out) {
  uint32_t len = 0;
  if (!recv_all(fd, &len, 4)) return false;
  out->resize(len);
  return len == 0 || recv_all(fd, &(*out)[0], len);
}

// ---------------------------------------------------------------------------
// TCPStore server
// ---------------------------------------------------------------------------

struct StoreServer {
  int listen_fd = -1;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::map<std::string, int64_t> counters;

  std::mutex conn_mu;
  std::vector<int> conn_fds;  // open client connections (for shutdown wakeup)

  ~StoreServer() { shutdown(); }

  void shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
      listen_fd = -1;
    }
    cv.notify_all();
    {
      // wake worker threads blocked in recv() on live client connections —
      // otherwise join below hangs until every remote client disconnects
      std::lock_guard<std::mutex> lk(conn_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread.joinable()) accept_thread.join();
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

  bool start(const std::string& host, int port, std::string* err) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) {
      *err = "socket() failed";
      return false;
    }
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    addr.sin_addr.s_addr =
        host.empty() ? INADDR_ANY : inet_addr(host.c_str());
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      *err = std::string("bind() failed: ") + strerror(errno);
      ::close(listen_fd);
      listen_fd = -1;
      return false;
    }
    if (::listen(listen_fd, 128) < 0) {
      *err = "listen() failed";
      ::close(listen_fd);
      listen_fd = -1;
      return false;
    }
    accept_thread = std::thread([this] { accept_loop(); });
    return true;
  }

  void accept_loop() {
    while (!stop.load()) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) break;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lk(conn_mu);
        conn_fds.push_back(fd);
      }
      workers.emplace_back([this, fd] { serve(fd); });
    }
  }

  void serve(int fd) {
    while (!stop.load()) {
      uint8_t cmd = 0;
      if (!recv_all(fd, &cmd, 1)) break;
      std::string key;
      if (!recv_str(fd, &key)) break;
      switch (cmd) {
        case kSet: {
          std::string val;
          if (!recv_str(fd, &val)) goto done;
          {
            std::lock_guard<std::mutex> lk(mu);
            kv[key] = std::move(val);
          }
          cv.notify_all();
          uint8_t st = kOk;
          if (!send_all(fd, &st, 1)) goto done;
          break;
        }
        case kGet: {
          int64_t timeout_ms = 0;
          if (!recv_all(fd, &timeout_ms, 8)) goto done;
          std::string val;
          uint8_t st = kOk;
          {
            std::unique_lock<std::mutex> lk(mu);
            auto deadline =
                Clock::now() + std::chrono::milliseconds(timeout_ms);
            while (!stop.load()) {
              auto it = kv.find(key);
              if (it != kv.end()) {
                val = it->second;
                break;
              }
              if (timeout_ms >= 0 &&
                  cv.wait_until(lk, deadline) == std::cv_status::timeout) {
                st = kTimeout;
                break;
              }
              if (timeout_ms < 0) cv.wait(lk);
            }
          }
          if (!send_all(fd, &st, 1)) goto done;
          if (st == kOk && !send_str(fd, val)) goto done;
          break;
        }
        case kAdd: {
          int64_t delta = 0;
          if (!recv_all(fd, &delta, 8)) goto done;
          int64_t result;
          {
            std::lock_guard<std::mutex> lk(mu);
            result = (counters[key] += delta);
            kv[key] = std::to_string(result);
          }
          cv.notify_all();
          uint8_t st = kOk;
          if (!send_all(fd, &st, 1) || !send_all(fd, &result, 8)) goto done;
          break;
        }
        case kCheck: {
          uint8_t st;
          {
            std::lock_guard<std::mutex> lk(mu);
            st = kv.count(key) ? kOk : kMissing;
          }
          if (!send_all(fd, &st, 1)) goto done;
          break;
        }
        case kDel: {
          {
            std::lock_guard<std::mutex> lk(mu);
            kv.erase(key);
            counters.erase(key);
          }
          uint8_t st = kOk;
          if (!send_all(fd, &st, 1)) goto done;
          break;
        }
        case kList: {
          std::string joined;
          {
            std::lock_guard<std::mutex> lk(mu);
            for (auto& p : kv) {
              if (p.first.rfind(key, 0) == 0) {
                joined += p.first;
                joined.push_back('\n');
              }
            }
          }
          uint8_t st = kOk;
          if (!send_all(fd, &st, 1) || !send_str(fd, joined)) goto done;
          break;
        }
        default:
          goto done;
      }
    }
  done:
    ::close(fd);
  }
};

// ---------------------------------------------------------------------------
// TCPStore client
// ---------------------------------------------------------------------------

struct StoreClient {
  int fd = -1;
  std::mutex mu;  // one request in flight per client

  ~StoreClient() {
    if (fd >= 0) ::close(fd);
  }

  bool connect_to(const std::string& host, int port, int64_t timeout_ms,
                  std::string* err) {
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    while (true) {
      fd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd < 0) {
        *err = "socket() failed";
        return false;
      }
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      hostent* he = ::gethostbyname(host.c_str());
      if (he != nullptr) {
        memcpy(&addr.sin_addr, he->h_addr, he->h_length);
      } else {
        addr.sin_addr.s_addr = inet_addr(host.c_str());
      }
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
          0) {
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd);
      fd = -1;
      if (Clock::now() >= deadline) {
        *err = "connect timeout to " + host + ":" + std::to_string(port);
        return false;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }
};

// ---------------------------------------------------------------------------
// handle registries
// ---------------------------------------------------------------------------

// The handle registries are heap-allocated and intentionally leaked: running
// their destructors at process exit would join server threads / destroy
// condvars that may still have waiters (blocked daemon threads), hanging exit.
static std::mutex g_reg_mu;
static int64_t g_next_handle = 1;
static auto& g_servers =
    *new std::unordered_map<int64_t, std::unique_ptr<StoreServer>>();
static auto& g_clients =
    *new std::unordered_map<int64_t, std::unique_ptr<StoreClient>>();

struct QueueObj {
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::deque<PyObject*> items;
  size_t capacity;
  bool closed = false;
};
// shared_ptr: in-flight push/pop keep the object alive after queue_destroy —
// destroying a condition_variable with live waiters blocks forever in glibc,
// so the destructor must only run once the last waiter is gone.
static auto& g_queues =
    *new std::unordered_map<int64_t, std::shared_ptr<QueueObj>>();

struct TraceEvent {
  std::string name;
  uint64_t tid;
  int64_t start_ns;
  int64_t end_ns;
  int64_t corr_id;
};
static std::mutex g_trace_mu;
static std::atomic<bool> g_trace_enabled{false};
static std::atomic<int64_t> g_trace_next_id{1};
static std::vector<TraceEvent> g_trace_done;
static std::unordered_map<int64_t, TraceEvent> g_trace_open;

struct StatEntry {
  int64_t current = 0;
  int64_t peak = 0;
};
static std::mutex g_stat_mu;
static std::map<std::string, StatEntry> g_stats;

static uint64_t this_tid() {
  return static_cast<uint64_t>(
      std::hash<std::thread::id>{}(std::this_thread::get_id()));
}

// ---------------------------------------------------------------------------
// Python: TCPStore
// ---------------------------------------------------------------------------

static PyObject* py_store_server_start(PyObject*, PyObject* args) {
  const char* host;
  int port;
  if (!PyArg_ParseTuple(args, "si", &host, &port)) return nullptr;
  auto srv = std::make_unique<StoreServer>();
  std::string err;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  ok = srv->start(host, port, &err);
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(PyExc_OSError, err.c_str());
    return nullptr;
  }
  std::lock_guard<std::mutex> lk(g_reg_mu);
  int64_t h = g_next_handle++;
  g_servers[h] = std::move(srv);
  return PyLong_FromLongLong(h);
}

static PyObject* py_store_server_stop(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  std::unique_ptr<StoreServer> srv;
  {
    std::lock_guard<std::mutex> lk(g_reg_mu);
    auto it = g_servers.find(h);
    if (it != g_servers.end()) {
      srv = std::move(it->second);
      g_servers.erase(it);
    }
  }
  if (srv) {
    Py_BEGIN_ALLOW_THREADS;
    srv->shutdown();
    srv.reset();
    Py_END_ALLOW_THREADS;
  }
  Py_RETURN_NONE;
}

static PyObject* py_store_connect(PyObject*, PyObject* args) {
  const char* host;
  int port;
  long long timeout_ms;
  if (!PyArg_ParseTuple(args, "siL", &host, &port, &timeout_ms)) return nullptr;
  auto cli = std::make_unique<StoreClient>();
  std::string err;
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  ok = cli->connect_to(host, port, timeout_ms, &err);
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(PyExc_TimeoutError, err.c_str());
    return nullptr;
  }
  std::lock_guard<std::mutex> lk(g_reg_mu);
  int64_t h = g_next_handle++;
  g_clients[h] = std::move(cli);
  return PyLong_FromLongLong(h);
}

static StoreClient* get_client(long long h) {
  std::lock_guard<std::mutex> lk(g_reg_mu);
  auto it = g_clients.find(h);
  return it == g_clients.end() ? nullptr : it->second.get();
}

static PyObject* py_store_close(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  std::lock_guard<std::mutex> lk(g_reg_mu);
  g_clients.erase(h);
  Py_RETURN_NONE;
}

static PyObject* py_store_set(PyObject*, PyObject* args) {
  long long h;
  const char* key;
  Py_buffer val;
  if (!PyArg_ParseTuple(args, "Lsy*", &h, &key, &val)) return nullptr;
  StoreClient* c = get_client(h);
  if (!c) {
    PyBuffer_Release(&val);
    PyErr_SetString(PyExc_ValueError, "bad store handle");
    return nullptr;
  }
  bool ok = false;
  uint8_t st = kError;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    uint8_t cmd = kSet;
    std::string k(key);
    std::string v(static_cast<const char*>(val.buf),
                  static_cast<size_t>(val.len));
    ok = send_all(c->fd, &cmd, 1) && send_str(c->fd, k) &&
         send_str(c->fd, v) && recv_all(c->fd, &st, 1);
  }
  Py_END_ALLOW_THREADS;
  PyBuffer_Release(&val);
  if (!ok || st != kOk) {
    PyErr_SetString(PyExc_ConnectionError, "store set failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* py_store_get(PyObject*, PyObject* args) {
  long long h;
  const char* key;
  long long timeout_ms;
  if (!PyArg_ParseTuple(args, "LsL", &h, &key, &timeout_ms)) return nullptr;
  StoreClient* c = get_client(h);
  if (!c) {
    PyErr_SetString(PyExc_ValueError, "bad store handle");
    return nullptr;
  }
  bool ok = false;
  uint8_t st = kError;
  std::string val;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    uint8_t cmd = kGet;
    std::string k(key);
    int64_t t = timeout_ms;
    ok = send_all(c->fd, &cmd, 1) && send_str(c->fd, k) &&
         send_all(c->fd, &t, 8) && recv_all(c->fd, &st, 1);
    if (ok && st == kOk) ok = recv_str(c->fd, &val);
  }
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "store get failed");
    return nullptr;
  }
  if (st == kTimeout) {
    PyErr_SetString(PyExc_TimeoutError, key);
    return nullptr;
  }
  if (st != kOk) {
    PyErr_SetString(PyExc_KeyError, key);
    return nullptr;
  }
  return PyBytes_FromStringAndSize(val.data(),
                                   static_cast<Py_ssize_t>(val.size()));
}

static PyObject* py_store_add(PyObject*, PyObject* args) {
  long long h;
  const char* key;
  long long delta;
  if (!PyArg_ParseTuple(args, "LsL", &h, &key, &delta)) return nullptr;
  StoreClient* c = get_client(h);
  if (!c) {
    PyErr_SetString(PyExc_ValueError, "bad store handle");
    return nullptr;
  }
  bool ok = false;
  uint8_t st = kError;
  int64_t result = 0;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    uint8_t cmd = kAdd;
    std::string k(key);
    int64_t d = delta;
    ok = send_all(c->fd, &cmd, 1) && send_str(c->fd, k) &&
         send_all(c->fd, &d, 8) && recv_all(c->fd, &st, 1) &&
         recv_all(c->fd, &result, 8);
  }
  Py_END_ALLOW_THREADS;
  if (!ok || st != kOk) {
    PyErr_SetString(PyExc_ConnectionError, "store add failed");
    return nullptr;
  }
  return PyLong_FromLongLong(result);
}

static PyObject* py_store_check(PyObject*, PyObject* args) {
  long long h;
  const char* key;
  if (!PyArg_ParseTuple(args, "Ls", &h, &key)) return nullptr;
  StoreClient* c = get_client(h);
  if (!c) {
    PyErr_SetString(PyExc_ValueError, "bad store handle");
    return nullptr;
  }
  bool ok = false;
  uint8_t st = kError;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    uint8_t cmd = kCheck;
    std::string k(key);
    ok = send_all(c->fd, &cmd, 1) && send_str(c->fd, k) &&
         recv_all(c->fd, &st, 1);
  }
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "store check failed");
    return nullptr;
  }
  return PyBool_FromLong(st == kOk);
}

static PyObject* py_store_delete(PyObject*, PyObject* args) {
  long long h;
  const char* key;
  if (!PyArg_ParseTuple(args, "Ls", &h, &key)) return nullptr;
  StoreClient* c = get_client(h);
  if (!c) {
    PyErr_SetString(PyExc_ValueError, "bad store handle");
    return nullptr;
  }
  bool ok = false;
  uint8_t st = kError;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    uint8_t cmd = kDel;
    std::string k(key);
    ok = send_all(c->fd, &cmd, 1) && send_str(c->fd, k) &&
         recv_all(c->fd, &st, 1);
  }
  Py_END_ALLOW_THREADS;
  if (!ok) {
    PyErr_SetString(PyExc_ConnectionError, "store delete failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* py_store_list(PyObject*, PyObject* args) {
  long long h;
  const char* prefix;
  if (!PyArg_ParseTuple(args, "Ls", &h, &prefix)) return nullptr;
  StoreClient* c = get_client(h);
  if (!c) {
    PyErr_SetString(PyExc_ValueError, "bad store handle");
    return nullptr;
  }
  bool ok = false;
  uint8_t st = kError;
  std::string joined;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    uint8_t cmd = kList;
    std::string k(prefix);
    ok = send_all(c->fd, &cmd, 1) && send_str(c->fd, k) &&
         recv_all(c->fd, &st, 1) && recv_str(c->fd, &joined);
  }
  Py_END_ALLOW_THREADS;
  if (!ok || st != kOk) {
    PyErr_SetString(PyExc_ConnectionError, "store list failed");
    return nullptr;
  }
  PyObject* lst = PyList_New(0);
  size_t pos = 0;
  while (pos < joined.size()) {
    size_t nl = joined.find('\n', pos);
    if (nl == std::string::npos) break;
    PyObject* s = PyUnicode_FromStringAndSize(joined.data() + pos,
                                              static_cast<Py_ssize_t>(nl - pos));
    PyList_Append(lst, s);
    Py_DECREF(s);
    pos = nl + 1;
  }
  return lst;
}

// ---------------------------------------------------------------------------
// Python: BlockingQueue
// ---------------------------------------------------------------------------

static PyObject* py_queue_create(PyObject*, PyObject* args) {
  long long capacity;
  if (!PyArg_ParseTuple(args, "L", &capacity)) return nullptr;
  auto q = std::make_shared<QueueObj>();
  q->capacity = static_cast<size_t>(capacity > 0 ? capacity : 1);
  std::lock_guard<std::mutex> lk(g_reg_mu);
  int64_t h = g_next_handle++;
  g_queues[h] = std::move(q);
  return PyLong_FromLongLong(h);
}

static std::shared_ptr<QueueObj> get_queue(long long h) {
  std::lock_guard<std::mutex> lk(g_reg_mu);
  auto it = g_queues.find(h);
  return it == g_queues.end() ? nullptr : it->second;
}

static PyObject* py_queue_push(PyObject*, PyObject* args) {
  long long h;
  PyObject* obj;
  long long timeout_ms;
  if (!PyArg_ParseTuple(args, "LOL", &h, &obj, &timeout_ms)) return nullptr;
  std::shared_ptr<QueueObj> q = get_queue(h);
  if (!q) {
    PyErr_SetString(PyExc_ValueError, "bad queue handle");
    return nullptr;
  }
  bool pushed = false, closed = false;
  Py_INCREF(obj);
  Py_BEGIN_ALLOW_THREADS;
  {
    std::unique_lock<std::mutex> lk(q->mu);
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    while (!q->closed && q->items.size() >= q->capacity) {
      if (timeout_ms < 0) {
        q->cv_push.wait(lk);
      } else if (q->cv_push.wait_until(lk, deadline) ==
                 std::cv_status::timeout) {
        break;
      }
    }
    if (q->closed) {
      closed = true;
    } else if (q->items.size() < q->capacity) {
      q->items.push_back(obj);
      pushed = true;
      q->cv_pop.notify_one();
    }
  }
  Py_END_ALLOW_THREADS;
  if (!pushed) Py_DECREF(obj);
  if (closed) {
    PyErr_SetString(PyExc_BrokenPipeError, "queue closed");
    return nullptr;
  }
  return PyBool_FromLong(pushed);
}

static PyObject* py_queue_pop(PyObject*, PyObject* args) {
  long long h;
  long long timeout_ms;
  if (!PyArg_ParseTuple(args, "LL", &h, &timeout_ms)) return nullptr;
  std::shared_ptr<QueueObj> q = get_queue(h);
  if (!q) {
    PyErr_SetString(PyExc_ValueError, "bad queue handle");
    return nullptr;
  }
  PyObject* obj = nullptr;
  bool closed_empty = false, timed_out = false;
  Py_BEGIN_ALLOW_THREADS;
  {
    std::unique_lock<std::mutex> lk(q->mu);
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    while (q->items.empty() && !q->closed) {
      if (timeout_ms < 0) {
        q->cv_pop.wait(lk);
      } else if (q->cv_pop.wait_until(lk, deadline) ==
                 std::cv_status::timeout) {
        timed_out = true;
        break;
      }
    }
    if (!q->items.empty()) {
      obj = q->items.front();
      q->items.pop_front();
      q->cv_push.notify_one();
    } else if (q->closed) {
      closed_empty = true;
    }
  }
  Py_END_ALLOW_THREADS;
  if (obj != nullptr) return obj;  // ref transferred
  if (closed_empty) {
    PyErr_SetString(PyExc_StopIteration, "queue closed");
    return nullptr;
  }
  if (timed_out) {
    PyErr_SetString(PyExc_TimeoutError, "queue pop timeout");
    return nullptr;
  }
  PyErr_SetString(PyExc_RuntimeError, "queue pop failed");
  return nullptr;
}

static PyObject* py_queue_close(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  std::shared_ptr<QueueObj> q = get_queue(h);
  if (!q) Py_RETURN_NONE;
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->cv_pop.notify_all();
  q->cv_push.notify_all();
  Py_RETURN_NONE;
}

static PyObject* py_queue_size(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  std::shared_ptr<QueueObj> q = get_queue(h);
  if (!q) {
    PyErr_SetString(PyExc_ValueError, "bad queue handle");
    return nullptr;
  }
  std::lock_guard<std::mutex> lk(q->mu);
  return PyLong_FromSize_t(q->items.size());
}

static PyObject* py_queue_destroy(PyObject*, PyObject* args) {
  long long h;
  if (!PyArg_ParseTuple(args, "L", &h)) return nullptr;
  std::shared_ptr<QueueObj> q;
  {
    std::lock_guard<std::mutex> lk(g_reg_mu);
    auto it = g_queues.find(h);
    if (it != g_queues.end()) {
      q = it->second;
      g_queues.erase(it);
    }
  }
  if (q) {
    // close + wake waiters, then drain item refs under the GIL; the QueueObj
    // itself is freed by whichever thread drops the LAST shared_ptr, after
    // every in-flight push/pop has left the condvars
    std::deque<PyObject*> leftovers;
    {
      std::lock_guard<std::mutex> lk(q->mu);
      q->closed = true;
      leftovers.swap(q->items);
    }
    q->cv_pop.notify_all();
    q->cv_push.notify_all();
    for (PyObject* o : leftovers) Py_DECREF(o);
  }
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// Python: host tracer
// ---------------------------------------------------------------------------

static PyObject* py_tracer_enable(PyObject*, PyObject* args) {
  int flag;
  if (!PyArg_ParseTuple(args, "p", &flag)) return nullptr;
  g_trace_enabled.store(flag != 0);
  Py_RETURN_NONE;
}

static PyObject* py_tracer_enabled(PyObject*, PyObject*) {
  return PyBool_FromLong(g_trace_enabled.load());
}

static PyObject* py_tracer_begin(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  if (!g_trace_enabled.load()) return PyLong_FromLongLong(0);
  int64_t id = g_trace_next_id.fetch_add(1);
  TraceEvent ev;
  ev.name = name;
  ev.tid = this_tid();
  ev.start_ns = now_ns();
  ev.end_ns = 0;
  ev.corr_id = id;
  {
    std::lock_guard<std::mutex> lk(g_trace_mu);
    g_trace_open.emplace(id, std::move(ev));
  }
  return PyLong_FromLongLong(id);
}

static PyObject* py_tracer_end(PyObject*, PyObject* args) {
  long long id;
  if (!PyArg_ParseTuple(args, "L", &id)) return nullptr;
  if (id == 0) Py_RETURN_NONE;
  std::lock_guard<std::mutex> lk(g_trace_mu);
  auto it = g_trace_open.find(id);
  if (it != g_trace_open.end()) {
    it->second.end_ns = now_ns();
    g_trace_done.push_back(std::move(it->second));
    g_trace_open.erase(it);
  }
  Py_RETURN_NONE;
}

static PyObject* py_tracer_instant(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  if (!g_trace_enabled.load()) Py_RETURN_NONE;
  TraceEvent ev;
  ev.name = name;
  ev.tid = this_tid();
  ev.start_ns = now_ns();
  ev.end_ns = ev.start_ns;
  ev.corr_id = g_trace_next_id.fetch_add(1);
  std::lock_guard<std::mutex> lk(g_trace_mu);
  g_trace_done.push_back(std::move(ev));
  Py_RETURN_NONE;
}

static PyObject* py_tracer_drain(PyObject*, PyObject*) {
  std::vector<TraceEvent> evs;
  {
    std::lock_guard<std::mutex> lk(g_trace_mu);
    evs.swap(g_trace_done);
  }
  PyObject* lst = PyList_New(static_cast<Py_ssize_t>(evs.size()));
  for (size_t i = 0; i < evs.size(); ++i) {
    PyObject* t = Py_BuildValue("(sKLL)", evs[i].name.c_str(),
                                static_cast<unsigned long long>(evs[i].tid),
                                static_cast<long long>(evs[i].start_ns),
                                static_cast<long long>(evs[i].end_ns));
    PyList_SET_ITEM(lst, static_cast<Py_ssize_t>(i), t);
  }
  return lst;
}

static PyObject* py_tracer_clear(PyObject*, PyObject*) {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  g_trace_done.clear();
  g_trace_open.clear();
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// Python: stat registry
// ---------------------------------------------------------------------------

static PyObject* py_stat_update(PyObject*, PyObject* args) {
  const char* name;
  long long delta;
  if (!PyArg_ParseTuple(args, "sL", &name, &delta)) return nullptr;
  std::lock_guard<std::mutex> lk(g_stat_mu);
  StatEntry& e = g_stats[name];
  e.current += delta;
  if (e.current > e.peak) e.peak = e.current;
  return PyLong_FromLongLong(e.current);
}

static PyObject* py_stat_get(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  std::lock_guard<std::mutex> lk(g_stat_mu);
  StatEntry& e = g_stats[name];
  return Py_BuildValue("(LL)", static_cast<long long>(e.current),
                       static_cast<long long>(e.peak));
}

static PyObject* py_stat_reset(PyObject*, PyObject* args) {
  const char* name;
  if (!PyArg_ParseTuple(args, "s", &name)) return nullptr;
  std::lock_guard<std::mutex> lk(g_stat_mu);
  g_stats.erase(name);
  Py_RETURN_NONE;
}

static PyObject* py_stat_all(PyObject*, PyObject*) {
  std::lock_guard<std::mutex> lk(g_stat_mu);
  PyObject* d = PyDict_New();
  for (auto& p : g_stats) {
    PyObject* v = Py_BuildValue("(LL)", static_cast<long long>(p.second.current),
                                static_cast<long long>(p.second.peak));
    PyDict_SetItemString(d, p.first.c_str(), v);
    Py_DECREF(v);
  }
  return d;
}

static PyObject* py_monotonic_ns(PyObject*, PyObject*) {
  return PyLong_FromLongLong(now_ns());
}

// ---------------------------------------------------------------------------
// module
// ---------------------------------------------------------------------------

static PyMethodDef kMethods[] = {
    {"store_server_start", py_store_server_start, METH_VARARGS, nullptr},
    {"store_server_stop", py_store_server_stop, METH_VARARGS, nullptr},
    {"store_connect", py_store_connect, METH_VARARGS, nullptr},
    {"store_close", py_store_close, METH_VARARGS, nullptr},
    {"store_set", py_store_set, METH_VARARGS, nullptr},
    {"store_get", py_store_get, METH_VARARGS, nullptr},
    {"store_add", py_store_add, METH_VARARGS, nullptr},
    {"store_check", py_store_check, METH_VARARGS, nullptr},
    {"store_delete", py_store_delete, METH_VARARGS, nullptr},
    {"store_list", py_store_list, METH_VARARGS, nullptr},
    {"queue_create", py_queue_create, METH_VARARGS, nullptr},
    {"queue_push", py_queue_push, METH_VARARGS, nullptr},
    {"queue_pop", py_queue_pop, METH_VARARGS, nullptr},
    {"queue_close", py_queue_close, METH_VARARGS, nullptr},
    {"queue_size", py_queue_size, METH_VARARGS, nullptr},
    {"queue_destroy", py_queue_destroy, METH_VARARGS, nullptr},
    {"tracer_enable", py_tracer_enable, METH_VARARGS, nullptr},
    {"tracer_enabled", py_tracer_enabled, METH_NOARGS, nullptr},
    {"tracer_begin", py_tracer_begin, METH_VARARGS, nullptr},
    {"tracer_end", py_tracer_end, METH_VARARGS, nullptr},
    {"tracer_instant", py_tracer_instant, METH_VARARGS, nullptr},
    {"tracer_drain", py_tracer_drain, METH_NOARGS, nullptr},
    {"tracer_clear", py_tracer_clear, METH_NOARGS, nullptr},
    {"stat_update", py_stat_update, METH_VARARGS, nullptr},
    {"stat_get", py_stat_get, METH_VARARGS, nullptr},
    {"stat_reset", py_stat_reset, METH_VARARGS, nullptr},
    {"stat_all", py_stat_all, METH_NOARGS, nullptr},
    {"monotonic_ns", py_monotonic_ns, METH_NOARGS, nullptr},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_native",
    "paddle_tpu native runtime tier (store/queue/tracer/stats)", -1, kMethods,
    nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&kModule); }
