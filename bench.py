"""Benchmark: Llama training tokens/sec/chip (BASELINE.md north-star metric).

Runs the full compiled training step (forward + backward + AdamW in one XLA
executable, bf16 AMP O2 with fp32 master weights) on the available chip and
prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.50 — the north-star bar is ">50% of H100
tokens/sec/chip", which at matched parallelism is an efficiency bar: 1.0 means
the model FLOPs utilization on this chip reaches 50%.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _peak_bf16_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v6e": 918e12, "v6": 918e12,
        "v5p": 459e12,
        "v5e": 197e12, "v5litepod": 197e12, "v5lite": 197e12,
        "v4": 275e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # default to v5e-class


def _progress(msg):
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def main(scan_layers=True):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import amp, jit, optimizer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    _progress("backend init")
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # scan_layers: the decoder stack compiles as ONE lax.scan body, so
        # compile time (the remote-compile tunnel's bottleneck) is O(1) in
        # depth instead of O(24 layers)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=24,
                          num_attention_heads=16, num_key_value_heads=16,
                          max_position_embeddings=1024,
                          scan_layers=scan_layers)
        batch, seq, iters = 4, 1024, 20
    else:  # CPU smoke (driver sanity / local dev)
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=176, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=128,
                          scan_layers=scan_layers)
        batch, seq, iters = 2, 64, 3

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters(), multi_precision=True)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    n_params = model.num_params()

    def loss_fn(ids, labels):
        _, loss = model(ids, labels=labels)
        return loss

    step = jit.TrainStep(loss_fn, opt)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # Eager discovery pass on a tiny batch (the unfused eager tape holds every
    # vjp residual — keep it off the big shape), then compile + warm the real
    # shape (the pure step is shape-polymorphic; jit retraces per shape).
    warm_ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (1, 128)))
    warm_labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (1, 128)))
    _progress(f"model built ({n_params/1e6:.0f}M params); eager discovery "
              f"pass starting")
    step(warm_ids, warm_labels)
    _progress("discovery done; compiling the fused train step")
    loss = step(ids, labels)
    float(loss)
    _progress("compiled; timing")

    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    final_loss = float(loss)  # blocks on the device
    elapsed = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / elapsed

    # Model FLOPs: 6*P per token (fwd+bwd) + attention score/context terms
    att_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + att_flops
    mfu = tokens_per_sec * flops_per_token / _peak_bf16_flops(dev)
    if not on_tpu:
        mfu = 0.0  # CPU MFU vs TPU peak is meaningless

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "detail": {
            "model": "llama",
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "iters": iters,
            "final_loss": round(final_loss, 4),
            "mfu": round(mfu, 4),
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "amp": "O2 bf16 + fp32 master",
        },
    }))


if __name__ == "__main__":
    try:
        try:
            main(scan_layers=True)
        except Exception:
            # self-heal chain: scanned stack -> unrolled stack -> unrolled
            # with the Pallas kernel tier disabled (pure XLA). Same metric
            # either way; only compile time / kernel choice differ.
            import traceback
            traceback.print_exc(file=sys.stderr)
            try:
                _progress("scan_layers path failed; retrying unrolled")
                main(scan_layers=False)
            except Exception:
                traceback.print_exc(file=sys.stderr)
                _progress("retrying with Pallas kernels disabled")
                import paddle_tpu
                paddle_tpu.set_flags({
                    "FLAGS_use_pallas_attention": False,
                    "FLAGS_use_pallas_rmsnorm": False,
                    "FLAGS_use_pallas_adamw": False,
                })
                main(scan_layers=False)
    except Exception as e:  # still emit the one JSON line the driver records
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "detail": {"error": f"{type(e).__name__}: {e}"[:300]},
        }))
        sys.exit(0)
