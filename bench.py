"""Benchmark: Llama training tokens/sec/chip (BASELINE.md north-star metric).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.50 — the north-star bar is ">50% of H100
tokens/sec/chip", which at matched parallelism is an efficiency bar: 1.0 means
the model FLOPs utilization on this chip reaches 50%.

Structure (wedge-proof): the parent process NEVER imports jax. It
  1. probes TPU health in a timeout-bounded subprocess (a wedged axon relay
     hangs `jax.devices()` indefinitely — observed all of round 1);
  2. if healthy, runs the real bench in a child (`--inproc`) with a
     self-imposed timeout under the driver's budget, SIGTERM-first so the
     axon claim is released cleanly;
  3. on probe failure / child timeout, runs a CPU-proxy child with the axon
     sitecustomize stripped from PYTHONPATH (immune to the wedge) so the
     driver ALWAYS records a parsed line — tagged "tpu": false.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

_T0 = time.perf_counter()
_REPO_DIR = os.path.dirname(os.path.abspath(__file__))

# 150 s proved too thin: cold plugin init alone exceeds 90 s (round-3
# window log), so a healthy-but-cold relay read as wedged and the round
# artifact recorded the CPU proxy. 330 s = cold init + jax.devices() with
# margin, still far under the TPU budget.
PROBE_TIMEOUT = int(os.environ.get("GRAFT_BENCH_PROBE_TIMEOUT", "330"))
TPU_TIMEOUT = int(os.environ.get("GRAFT_BENCH_TPU_TIMEOUT", "1080"))
CPU_TIMEOUT = int(os.environ.get("GRAFT_BENCH_CPU_TIMEOUT", "240"))
SNAPSHOT_PATH = os.path.join(_REPO_DIR, "BENCH_TPU_SNAPSHOT.json")


def _progress(msg):
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


def _peak_bf16_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v6e": 918e12, "v6": 918e12,
        "v5p": 459e12,
        "v5e": 197e12, "v5litepod": 197e12, "v5lite": 197e12,
        "v5 lite": 197e12,  # axon reports device_kind "TPU v5 lite"
        "v4": 275e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # default to v5e-class


# ---------------------------------------------------------------------------
# In-process bench body (runs in a child)
# ---------------------------------------------------------------------------

def main(scan_layers=True, size="large"):
    import numpy as np

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import amp, jit, optimizer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    _progress("backend init")
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu and size == "large":
        # Sized to the chip (VERDICT r3 #1): ~0.55B params → 7.7 GB of
        # bf16 weight + fp32 master + Adam m/v on a 16 GB v5e; seq 2048
        # through the flash-attention Pallas kernel; head_dim 128 and
        # hidden 1536 (12×128 lanes) to fill the MXU.
        # recompute "selective" (dots_with_no_batch_dims_saveable), NOT
        # "full": full remat replays the whole forward in the backward —
        # ~25% of the step is uncounted FLOPs and measured MFU caps at
        # 0.75× the hardware utilization. Selective keeps matmul outputs
        # resident (~4.2 GB at batch 4 × seq 2048) and replays only the
        # cheap elementwise chains, so measured MFU ≈ true MFU.
        # scan_layers: the decoder stack compiles as ONE lax.scan body, so
        # compile time (the remote-compile tunnel's bottleneck) is O(1) in
        # depth instead of O(16 layers).
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4096, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=12,
                          max_position_embeddings=2048,
                          scan_layers=scan_layers, use_recompute=True,
                          recompute_granularity="selective")
        batch, seq, iters = 4, 2048, 15
    elif on_tpu and size == "medium":
        # memory-safe middle tier (~0.35B, ≈9 GB est.): keeps flash +
        # selective remat + seq 2048 — the MFU-carrying features — so an
        # OOM on the large config still produces a flash-enabled number
        # at the HBM-relevant sequence length
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1152,
                          intermediate_size=3072, num_hidden_layers=16,
                          num_attention_heads=9, num_key_value_heads=9,
                          max_position_embeddings=2048,
                          scan_layers=scan_layers, use_recompute=True,
                          recompute_granularity="selective")
        batch, seq, iters = 4, 2048, 15
    elif on_tpu:
        # smallest fallback config (OOM / compile-budget self-heal); the
        # round-3 snapshot config — known to run on the chip
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=24,
                          num_attention_heads=16, num_key_value_heads=16,
                          max_position_embeddings=1024,
                          scan_layers=scan_layers)
        batch, seq, iters = 4, 1024, 20
    else:  # CPU proxy (relay down / local dev) — same code path, tiny shape
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=176, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=128,
                          scan_layers=scan_layers)
        batch, seq, iters = 2, 64, 3

    from paddle_tpu.perf import compile_cache as perf_cc
    if on_tpu:
        # measure flash (block_q, block_k) tilings once per shape and run
        # the headline number at the winner (autotune is trace-safe)
        paddle.set_flags({"FLAGS_flash_autotune": True})
        # persistent compilation cache: the first Llama compile through the
        # remote-compile tunnel has exceeded 15 min; with the cache, a
        # retried/repeated bench (or the next round) skips it entirely.
        # PADDLE_COMPILE_CACHE overrides the default repo-local directory.
        cache_dir = (os.environ.get("PADDLE_COMPILE_CACHE")
                     or os.path.join(_REPO_DIR, ".jax_cache"))
        if not perf_cc.enable_persistent_cache(cache_dir):
            _progress("persistent compilation cache unavailable")

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters(), multi_precision=True)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    n_params = model.num_params()

    def loss_fn(ids, labels):
        _, loss = model(ids, labels=labels)
        return loss

    # op-level observatory: capture the step executable's cost profile
    # at its warm transitions (OPPROF_r*.json + the opprof: guard lane)
    from paddle_tpu.observability import opprof
    opprof.enable()
    opprof.reset_captures()

    step = jit.TrainStep(loss_fn, opt, opprof_label="bench.train_step")

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)))

    # Eager discovery pass on a tiny batch (the unfused eager tape holds every
    # vjp residual — keep it off the big shape), then compile + warm the real
    # shape (the pure step is shape-polymorphic; jit retraces per shape).
    warm_ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (1, 128)))
    warm_labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (1, 128)))
    _progress(f"model built ({n_params/1e6:.0f}M params); eager discovery "
              f"pass starting")
    step(warm_ids, warm_labels)
    _progress("discovery done; compiling the fused train step")
    loss = step(ids, labels)
    float(loss)
    _progress("compiled; timing")

    from paddle_tpu.observability import span

    t0 = time.perf_counter()
    for _ in range(iters):
        with span("bench_train_step"):
            loss = step(ids, labels)
    final_loss = float(loss)  # blocks on the device
    elapsed = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / elapsed
    # fresh child process, so the perf counters ARE this bench's compile
    # story: misses = programs built, compile_time_s = trace+compile spend
    compile_stats = perf_cc.compile_metrics()

    # Model FLOPs: 6*P per token (fwd+bwd) + attention score/context terms
    att_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + att_flops
    mfu = tokens_per_sec * flops_per_token / _peak_bf16_flops(dev)
    if not on_tpu:
        mfu = 0.0  # CPU MFU vs TPU peak is meaningless

    detail = {
        "model": "llama",
        "tpu": on_tpu,
        "params": n_params,
        "batch": batch,
        "seq": seq,
        "iters": iters,
        "final_loss": round(final_loss, 4),
        "mfu": round(mfu, 4),
        "steady_step_s": round(elapsed / iters, 5),
        "compile_time_s": compile_stats["compile_time_s"],
        "compile_cache_hits": compile_stats["compile_cache_hits"],
        "compile_cache_misses": compile_stats["compile_cache_misses"],
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "amp": "O2 bf16 + fp32 master",
        "recompute": getattr(cfg, "recompute_granularity", None)
        if cfg.use_recompute else "off",
        # the Pallas kernel only routes on TPU; off-TPU the flag is moot
        "flash": bool(on_tpu and paddle.get_flags(
            ["FLAGS_use_pallas_attention"])["FLAGS_use_pallas_attention"]),
    }
    if on_tpu:
        detail["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())
    # op-level profile: split the roofline gap per op class, embed the
    # top-k class cost table + executable fingerprint (bench_guard
    # chains these alongside last_tpu), persist the OPPROF artifact
    try:
        from paddle_tpu.observability import roofline_attr
        attr = roofline_attr.observe_train_step(
            elapsed / iters, observed_mfu=mfu, tokens=batch * seq,
            params=n_params)
        gap_split = opprof.publish_gap_attribution(attr) if attr else None
        summary = opprof.bench_summary()
        if summary is not None:
            detail["opprof"] = summary
            opp_path = opprof.write_artifact(
                _REPO_DIR, tpu=on_tpu, gap_attribution=gap_split,
                extra={"bench_step_s": round(elapsed / iters, 5),
                       "bench_mfu": round(mfu, 4)})
            if opp_path:
                detail["opprof"]["artifact"] = os.path.basename(opp_path)
                _progress(f"op profile: {opp_path} "
                          f"(top {summary['top_op_classes'][:2]})")
    except Exception as e:  # profiling must never sink the bench number
        _progress(f"op profile failed: {type(e).__name__}: {e}")
    # telemetry snapshot rides alongside (stderr + file only — stdout is
    # the one-JSON-line contract)
    try:
        from paddle_tpu.observability import load_jsonl, write_jsonl
        snap_path = os.path.join(_REPO_DIR, "BENCH_TELEMETRY.jsonl")
        write_jsonl(snap_path, extra={"bench": "llama", "tpu": on_tpu})
        detail["telemetry_series"] = len(load_jsonl(snap_path))
        _progress(f"telemetry snapshot: {snap_path} "
                  f"({detail['telemetry_series']} series)")
    except Exception as e:  # telemetry must never sink the bench number
        _progress(f"telemetry snapshot failed: {type(e).__name__}: {e}")
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "detail": detail,
    }), flush=True)


class _AttemptTimeout(Exception):
    pass


class _deadline:
    """SIGALRM-bounded attempt: a slow-but-not-raising config (e.g. a
    compile crawling through the remote-compile tunnel) must not starve
    the later fallbacks — the parent would SIGTERM the whole child and
    the result would silently downgrade to the CPU proxy."""

    def __init__(self, seconds):
        self.seconds = int(seconds) if seconds else 0

    def __enter__(self):
        if self.seconds > 0:
            def _raise(signum, frame):
                raise _AttemptTimeout(f"attempt exceeded {self.seconds}s")
            self._old = signal.signal(signal.SIGALRM, _raise)
            signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        if self.seconds > 0:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, self._old)
        return False


def _inproc():
    """Child entry: self-heal chain large -> medium -> small -> unrolled
    -> no-Pallas.

    The large/medium tiers only exist on TPU (the CPU proxy ignores
    `size`, so retrying them off-TPU would just run the identical config
    twice). Large gets ~45% of the TPU budget and medium ~25%; a timeout
    advances the chain instead of eating the whole child deadline.
    """
    import traceback

    import jax
    on_tpu = False
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        traceback.print_exc(file=sys.stderr)

    attempts = []
    if on_tpu:
        attempts.append(("large", True, int(TPU_TIMEOUT * 0.45)))
        attempts.append(("medium", True, int(TPU_TIMEOUT * 0.25)))
    attempts += [("small", True, 0), ("small", False, 0)]
    for size, scan, bound in attempts:
        try:
            with _deadline(bound):
                main(scan_layers=scan, size=size)
            return
        except Exception:
            traceback.print_exc(file=sys.stderr)
            _progress(f"attempt (size={size}, scan={scan}) failed; "
                      f"trying next fallback")
    _progress("retrying with Pallas kernels disabled")
    import paddle_tpu
    paddle_tpu.set_flags({
        "FLAGS_use_pallas_attention": False,
        "FLAGS_use_pallas_rmsnorm": False,
        "FLAGS_use_pallas_adamw": False,
    })
    main(scan_layers=False, size="small")


# ---------------------------------------------------------------------------
# Parent orchestration (never imports jax)
# ---------------------------------------------------------------------------

def _sanitized_env(n_devices=1):
    """Env with the axon sitecustomize stripped: immune to a wedged relay."""
    import __graft_entry__ as graft
    env = dict(os.environ)
    graft.force_cpu_env(env, n_devices)
    graft.strip_axon_pythonpath(env)
    return env


def _communicate(proc, timeout):
    """communicate() with SIGTERM-first on timeout (a SIGKILL mid-TPU-use
    leaves a dead pool claim that wedges the relay for every later process)."""
    try:
        return proc.communicate(timeout=timeout)[0], False
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            return proc.communicate(timeout=30)[0], True
        except subprocess.TimeoutExpired:
            proc.kill()
            return proc.communicate()[0], True


def _probe_tpu() -> bool:
    """Is the TPU reachable? Bounded subprocess so a wedge can't hang us."""
    _progress(f"probing TPU health (timeout {PROBE_TIMEOUT}s)")
    code = ("import jax; ds = jax.devices(); "
            "assert ds[0].platform == 'tpu', ds; print(ds)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT,
                            text=True, cwd=_REPO_DIR)
    out, timed_out = _communicate(proc, PROBE_TIMEOUT)
    if timed_out:
        _progress("TPU probe timed out — relay wedged or unreachable")
        return False
    if proc.returncode == 0:
        _progress(f"TPU healthy: {(out or '').strip()[:120]}")
        return True
    _progress(f"TPU probe failed rc={proc.returncode}: "
              f"{(out or '').strip()[-200:]}")
    return False


def _run_child(env, timeout):
    """Run `bench.py --inproc`; return the parsed JSON line or None.

    A child that exited non-zero or whose line carries detail.error is a
    FAILED run (value 0.0) — report None so the caller falls back instead of
    recording an empty number.
    """
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__),
                             "--inproc"],
                            stdout=subprocess.PIPE, stderr=sys.stderr,
                            text=True, cwd=_REPO_DIR, env=env)
    out, timed_out = _communicate(proc, timeout)
    if timed_out:
        _progress(f"bench child timed out after {timeout}s")
    if proc.returncode != 0:
        _progress(f"bench child failed rc={proc.returncode}")
        return None
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in parsed:
                if parsed.get("detail", {}).get("error"):
                    return None
                return parsed
    return None


def _persist_snapshot(result):
    """Keep the newest real-TPU number on disk so a later wedged window can
    still report it (VERDICT r3 #2)."""
    try:
        # atomic replace: a mid-write kill must not destroy the previous
        # good snapshot (the whole point of keeping it)
        tmp = SNAPSHOT_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
            f.write("\n")
        os.replace(tmp, SNAPSHOT_PATH)
    except OSError as e:
        _progress(f"could not persist TPU snapshot: {e}")


def _last_snapshot():
    """Most recent TPU snapshot (or None), stamped with a capture time —
    from its own detail if the run recorded one, else the file mtime."""
    try:
        with open(SNAPSHOT_PATH) as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not snap.get("detail", {}).get("tpu"):
        return None
    snap.setdefault("detail", {}).setdefault(
        "captured_at",
        time.strftime("%Y-%m-%dT%H:%M:%SZ",
                      time.gmtime(os.path.getmtime(SNAPSHOT_PATH))))
    return snap


def _orchestrate():
    tpu_ok = _probe_tpu()
    # snapshot BEFORE this run persists a new one: every emitted line —
    # including a healthy TPU run — chains the previous hardware point,
    # so trajectory tools never lose the thread across wedged windows
    prev_snap = _last_snapshot()
    result = None
    if tpu_ok:
        # spend the whole TPU budget minus what the probe already used
        budget = max(300, TPU_TIMEOUT - int(time.perf_counter() - _T0))
        _progress(f"running TPU bench (timeout {budget}s)")
        result = _run_child(dict(os.environ), budget)
        if result is None:
            _progress("TPU bench produced no line; falling back to CPU proxy")
        elif result.get("detail", {}).get("tpu"):
            _persist_snapshot(result)
    if result is None:
        _progress(f"running CPU-proxy bench (timeout {CPU_TIMEOUT}s)")
        result = _run_child(_sanitized_env(), CPU_TIMEOUT)
        if result is not None:
            result.setdefault("detail", {})["tpu"] = False
            if tpu_ok:
                result["detail"]["fallback"] = "tpu_bench_failed"
            else:
                result["detail"]["fallback"] = "tpu_unreachable"
    if result is None:  # still emit the one line the driver records
        result = {
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "detail": {"error": "all bench paths failed", "tpu": False},
        }
    # one machine-readable verdict on how this line relates to the TPU:
    # "ok" = fresh hardware number, "bench_failed" = TPU reachable but the
    # bench died (the number is a CPU proxy), "unreachable" = no TPU seen
    result["relay"] = ("ok" if result.get("detail", {}).get("tpu")
                       else "bench_failed" if tpu_ok else "unreachable")
    if prev_snap is not None:
        # a wedged window must not erase the hardware evidence: carry the
        # last healthy-window TPU number (honestly labeled with its capture
        # time) inside EVERY artifact, fallback or not
        result.setdefault("detail", {})["last_tpu"] = {
            "value": prev_snap.get("value"),
            "unit": prev_snap.get("unit"),
            "vs_baseline": prev_snap.get("vs_baseline"),
            "detail": prev_snap.get("detail"),
        }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    if "--inproc" in sys.argv:
        try:
            _inproc()
        except Exception as e:
            import traceback
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "llama_train_tokens_per_sec_per_chip",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "detail": {"error": f"{type(e).__name__}: {e}"[:300]},
            }), flush=True)
            sys.exit(1)
    else:
        _orchestrate()
        sys.exit(0)
