#!/usr/bin/env python
"""Attribution CLI over recorded request traces: slow-request
waterfalls, the fleet critical-path summary, and the goodput/waste
ledger (text or ``--json``).

Span sources, in precedence order:

  * ``--jsonl PATH``  — a span export (``TraceRecorder.export_jsonl``
    or any JSONL of span dicts),
  * ``--fleet DIR``   — a fleet telemetry spool (rank shards; torn
    tails and crashed ranks degrade to partial waterfalls flagged
    ``incomplete``, never an error),
  * default          — run the same in-process demo workload
    ``telemetry_dump`` uses (a ContinuousBatcher + a 2-replica
    gateway) and analyze the live recorder; ``--no-workload`` skips
    the traffic and reads whatever this process already recorded.

Output: the top-N slowest request waterfalls (critical path per
request), the aggregate critical-path self-time by span name, the
goodput ledger summary (chip-seconds by tenant/rung/phase plus the
waste taxonomy: bucket_pad / requeue_recompute /
evicted_prefix_recompute / speculation_rejected / recompile), and any
streaming anomaly findings (per-replica TTFT/TPOT spikes) derived from
the same traces. The lint lane runs ``trace_analyze.py --json`` over
the demo workload as a smoke gate; bench_gateway embeds the same
ledger numbers in ``BENCH_GATEWAY_r*.json`` for bench_guard.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _load_jsonl_spans(path: str):
    spans = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                spans.append(json.loads(line))
            except ValueError:
                continue               # torn tail line — keep going
    return spans


def analyze(waterfalls, top: int = 5) -> dict:
    """The full attribution payload for a set of waterfalls."""
    from paddle_tpu.observability.anomaly import AnomalyDetector
    from paddle_tpu.observability.ledger import ledger_from_waterfalls
    from paddle_tpu.observability.waterfall import critical_path_summary

    ledger = ledger_from_waterfalls(waterfalls)
    detector = AnomalyDetector()
    detector.observe_waterfalls(waterfalls)
    slowest = sorted(waterfalls, key=lambda w: -w.total_s)[:top]
    return {
        "n_traces": len(waterfalls),
        "incomplete": sum(1 for w in waterfalls if w.incomplete),
        "requests": [w.to_dict() for w in slowest],
        "critical_path_summary": critical_path_summary(waterfalls),
        "ledger": ledger.summary(),
        "findings": [f.to_dict() for f in detector.findings],
    }


def _render_text(payload: dict, waterfalls, top: int) -> str:
    from paddle_tpu.observability.waterfall import render_waterfall
    lines = [f"# {payload['n_traces']} trace(s), "
             f"{payload['incomplete']} incomplete — "
             f"top {min(top, payload['n_traces'])} by wall time"]
    slowest = sorted(waterfalls, key=lambda w: -w.total_s)[:top]
    for wf in slowest:
        lines.append("")
        lines.append(render_waterfall(wf))
    lines.append("")
    lines.append("# critical-path self time by span")
    for name, s in payload["critical_path_summary"].items():
        lines.append(f"  {name:<18s} {s * 1e3:10.2f}ms")
    led = payload["ledger"]
    lines.append("")
    lines.append(f"# goodput ledger: chip={led['chip_seconds'] * 1e3:.2f}ms "
                 f"goodput_frac={led['goodput_frac']:.4f}")
    for cat, s in led["waste_seconds"].items():
        lines.append(f"  waste.{cat:<26s} {s * 1e3:10.2f}ms")
    for row in led["attribution"][:10]:
        lines.append(f"  {row['tenant']}/{row['rung']}/{row['phase']:<10s} "
                     f"{row['seconds'] * 1e3:10.2f}ms")
    if payload["findings"]:
        lines.append("")
        lines.append("# anomaly findings")
        for f in payload["findings"]:
            lines.append(f"  {f['kind']} key={f['detail'].get('key')} "
                         f"score={f['detail'].get('score', 0):.1f}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--top", type=int, default=5,
                    help="slow requests to render (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full payload as JSON")
    ap.add_argument("--jsonl", metavar="PATH", default=None,
                    help="analyze a span JSONL export instead of the "
                         "live recorder")
    ap.add_argument("--fleet", metavar="DIR", default=None,
                    help="analyze a fleet telemetry spool directory")
    ap.add_argument("--no-workload", action="store_true",
                    help="live mode without the demo workload")
    ap.add_argument("--out", metavar="PATH", default=None,
                    help="write here instead of stdout")
    args = ap.parse_args(argv)

    from paddle_tpu.observability.waterfall import (build_waterfalls,
                                                    waterfalls_from_fleet)
    if args.jsonl:
        wfs = build_waterfalls(_load_jsonl_spans(args.jsonl))
    elif args.fleet:
        wfs = waterfalls_from_fleet(args.fleet)
    else:
        if not args.no_workload:
            import telemetry_dump
            telemetry_dump._demo_workload()
        from paddle_tpu.observability.waterfall import \
            waterfalls_from_recorder
        wfs = waterfalls_from_recorder()

    payload = analyze(wfs, top=args.top)
    text = (json.dumps(payload, indent=2) + "\n" if args.json
            else _render_text(payload, wfs, args.top))
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
