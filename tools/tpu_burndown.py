#!/usr/bin/env python
"""Per-kernel TPU hardware burndown (VERDICT r3 #3).

Round 3 burned an 8-hour relay window because one bad Mosaic compile (the
flash-dropout hardware-PRNG path) wedged the axon relay from *inside* a
monolithic `pytest -m tpu` run — every later kernel in the tier lost its
first hardware contact. This runner replaces that stage:

- each tier unit runs in its OWN subprocess (pytest node id), SIGTERM-first
  on timeout so a hung compile never leaves a dead pool claim;
- units are ordered safest -> riskiest: kernels that already compiled on
  hardware first, first-contact compiles after, and the known relay-killer
  (pltpu.prng_*) LAST;
- a `jax.devices()` health probe runs after every unit; if the relay
  stopped answering, the run ABORTS and the report names the culprit;
- results merge into TPU_BURNDOWN.json (per-unit status across windows)
  and append to TPU_TESTS.log for the round report.

Phases let the heal playbook interleave other artifacts between the safe
and risky halves (bench -> safe tier -> serving bench -> risky tier), so a
wedge in a first-contact compile can no longer take the serving number
down with it.

Reference analog: the per-arch device validation the reference runs for
every kernel (test/legacy_test/test_flash_attention.py over
phi/kernels/gpu/flash_attn_kernel.cu; autotune cache at
phi/kernels/autotune/cache.h:42) — here the device is one axon-relayed
v5e chip whose compile service wedges on certain failures, so validation
must be incremental and health-checked.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.environ.get("GRAFT_BURNDOWN_REPORT",
                        os.path.join(REPO, "TPU_BURNDOWN.json"))
LOG = os.environ.get("GRAFT_BURNDOWN_LOG",
                     os.path.join(REPO, "TPU_TESTS.log"))

# (name, pytest node id under tests/test_tpu_tier.py, phase, timeout_s)
# safe  = compiled on hardware in a previous window (round-3 flash fixes),
#         or skips without >=2 chips; first in line so profiles/serving
#         evidence lands before any first-contact compile can wedge.
# risky = first-contact Mosaic compiles, safest first; the dropout
#         hardware-PRNG units are LAST — that exact compile 500'd and
#         wedged the relay for 8+ hours on 2026-07-31 (TPU_PROBES.log).
UNITS = [
    ("flash_fwd", "test_flash_mosaic_forward", "safe", 480),
    ("flash_grads", "test_flash_mosaic_grads", "safe", 480),
    ("flash_gqa_mask_varlen", "test_flash_mosaic_gqa_mask_varlen",
     "safe", 480),
    ("flash_shapes", "test_flash_mosaic_arbitrary_and_short_seq",
     "safe", 480),
    ("serving_fused", "test_fused_serving_on_tpu", "safe", 600),
    ("serving_exact_no_retry", "test_paged_exactness_retry_free_on_tpu",
     "safe", 600),
    ("profile_flagship", "test_flagship_attention_step_profile",
     "safe", 600),
    ("profile_pipeline", "test_pipeline_bubble_profiles", "safe", 480),
    ("profile_ring", "test_ring_attention_overlap_trace", "safe", 480),
    ("rmsnorm", "test_rmsnorm_mosaic", "risky", 480),
    ("adamw", "test_adamw_mosaic", "risky", 480),
    ("block_sparse", "test_block_sparse_mosaic", "risky", 600),
    ("autotune", "test_flash_autotune_sweep", "risky", 900),
    ("dropout_prng_fwd",
     "test_flash_dropout_hw_prng_determinism_and_keep_rate", "risky", 480),
    ("dropout_prng_bwd",
     "test_flash_dropout_hw_prng_fwd_bwd_seed_coordinates", "risky", 480),
]

PROBE_TIMEOUT = int(os.environ.get("GRAFT_BURNDOWN_PROBE_TIMEOUT", "300"))


def _ts():
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _log(msg):
    line = f"{_ts()} [burndown] {msg}"
    print(line, flush=True)
    try:
        with open(LOG, "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


sys.path.insert(0, REPO)
# one copy of the SIGTERM-first bounded wait (a SIGKILL mid-TPU-use leaves
# a dead pool claim) — bench.py owns it; stdlib-only at import time
from bench import _communicate  # noqa: E402


# the subprocess currently holding (or probing) the TPU claim — the SIGTERM
# handler must pass the signal down before dying, or the playbook's outer
# `timeout` would orphan a pytest child mid-allocation: the exact dead-claim
# wedge this runner exists to prevent
_ACTIVE = {"proc": None}


def _on_sigterm(signum, frame):
    p = _ACTIVE.get("proc")
    if p is not None and p.poll() is None:
        try:
            p.send_signal(signal.SIGTERM)
            p.wait(timeout=25)
        except Exception:
            try:
                p.kill()
            except OSError:
                pass
    raise SystemExit(143)


def _probe(interpret: bool) -> bool:
    """Relay (or, interpreted, CPU backend) still answering?"""
    cmd = os.environ.get("GRAFT_BURNDOWN_PROBE_CMD")
    if cmd:  # test hook: orchestration tests script the health sequence
        try:
            return subprocess.run(cmd, shell=True, cwd=REPO,
                                  timeout=PROBE_TIMEOUT or 30).returncode == 0
        except subprocess.TimeoutExpired:
            return False
    if interpret:
        code = "import jax; assert jax.devices()"
        env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    else:
        code = ("import jax; ds = jax.devices(); "
                "assert ds[0].platform == 'tpu', ds")
        env = dict(os.environ)
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=env, cwd=REPO)
    _ACTIVE["proc"] = proc
    try:
        _, timed_out = _communicate(proc, PROBE_TIMEOUT)
    finally:
        _ACTIVE["proc"] = None
    return (not timed_out) and proc.returncode == 0


def _run_unit(name, node, timeout, interpret):
    env = dict(os.environ)
    if interpret:
        env["PADDLE_TPU_TIER_INTERPRET"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
    else:
        env["PADDLE_TPU_RUN_TPU_TESTS"] = "1"
    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "pytest",
         f"tests/test_tpu_tier.py::{node}", "-q", "--no-header", "-rA"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    _ACTIVE["proc"] = proc
    try:
        out, timed_out = _communicate(proc, timeout)
    finally:
        _ACTIVE["proc"] = None
    secs = round(time.perf_counter() - t0, 1)
    tail = (out or "").strip().splitlines()[-15:]
    if timed_out:
        status = "timeout"
    elif proc.returncode == 0:
        # an all-skip unit (e.g. multi-chip profiles on one chip) exits 0
        # with only 'N skipped' in the summary
        status = "passed" if " passed" in (out or "") else "skipped"
    else:
        status = "failed"
    return {"name": name, "node": node, "status": status,
            "rc": proc.returncode, "seconds": secs, "at": _ts(),
            "tail": "\n".join(tail)[-2000:]}


def _load_report():
    try:
        with open(REPORT) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"units": {}}


def _save_report(report):
    tmp = REPORT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    os.replace(tmp, REPORT)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--phase", choices=["safe", "risky", "all"],
                    default="all")
    ap.add_argument("--units", help="comma-separated unit names (overrides "
                    "--phase)")
    ap.add_argument("--budget", type=int, default=3600,
                    help="overall wall-clock budget (s); remaining units "
                    "are marked not_run when it runs out")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU self-check: run the same orchestration with "
                    "PADDLE_TPU_TIER_INTERPRET=1 (no hardware needed)")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args()

    if args.units:
        wanted = [n.strip() for n in args.units.split(",") if n.strip()]
        known = {u[0] for u in UNITS}
        unknown = [n for n in wanted if n not in known]
        if unknown:
            ap.error(f"unknown unit(s) {unknown}; known: {sorted(known)}")
        selected = [u for u in UNITS if u[0] in wanted]
    else:
        selected = [u for u in UNITS if args.phase in ("all", u[2])]
    if args.list:
        for name, node, phase, tmo in selected:
            print(f"{phase:5s} {name:24s} {node} ({tmo}s)")
        return 0

    mode = "interpret" if args.interpret else "hardware"
    _log(f"start phase={args.phase} units={[u[0] for u in selected]} "
         f"mode={mode}")
    # the playbook's outer `timeout` SIGTERMs us at the stage edge: forward
    # it to the child still holding the TPU claim, then record what happened
    signal.signal(signal.SIGTERM, _on_sigterm)
    report = _load_report()
    report["last_run"] = {"at": _ts(), "phase": args.phase, "mode": mode}

    if not _probe(args.interpret):
        _log("initial probe failed — relay wedged/unreachable; nothing run")
        report["last_run"]["result"] = "relay_down"
        _save_report(report)
        return 0

    deadline = time.perf_counter() + args.budget
    aborted = None
    try:
        _run_selected(selected, deadline, report, args)
        aborted = report.pop("_aborted_on", None)
    except SystemExit:
        report["last_run"]["result"] = "terminated"
        _save_report(report)
        _log("SIGTERM: child cleaned up, report saved")
        raise
    report["last_run"]["result"] = (
        f"aborted_after={aborted}" if aborted else "completed")
    _save_report(report)
    _log(f"done: {report['last_run']['result']}")
    return 2 if aborted else 0


def _run_selected(selected, deadline, report, args):
    for name, node, phase, tmo in selected:
        remaining = deadline - time.perf_counter()
        if remaining < 120:
            _log(f"budget exhausted before {name}; stopping")
            # never clobber a prior window's real result with 'not_run'
            if name not in report["units"]:
                report["units"][name] = {"name": name, "node": node,
                                         "status": "not_run", "at": _ts(),
                                         "why": "budget"}
            report["last_run"].setdefault("not_run", []).append(name)
            continue
        _log(f"unit {name} ({phase}) starting, timeout "
             f"{min(tmo, int(remaining))}s")
        res = _run_unit(name, node, min(tmo, int(remaining)), args.interpret)
        res["mode"] = report["last_run"]["mode"]
        report["units"][name] = res
        _log(f"unit {name}: {res['status']} ({res['seconds']}s)")
        _save_report(report)
        if not _probe(args.interpret):
            report["_aborted_on"] = name
            res["wedged_relay"] = True
            _log(f"HEALTH PROBE FAILED after unit {name} — relay wedged; "
                 f"aborting (culprit recorded)")
            return


if __name__ == "__main__":
    sys.exit(main())
