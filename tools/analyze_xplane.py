#!/usr/bin/env python
"""Summarize a jax.profiler xplane capture (VERDICT r3 weak #7).

The tpu tier captures xplane traces (profiles/pp_1f1b, profiles/pp_vpp,
profiles/llama_flash_step, profiles/ring_overlap) but raw .xplane.pb is
not quotable. This turns a capture into the numbers the round report
needs:

  - per-device busy time vs wall span -> duty cycle (for the pipeline
    schedule traces, 1 - duty is the measured BUBBLE ratio to put next
    to the plan-level predictions: VPP 0.158 vs 1F1B 0.273)
  - top-k ops by self time (where the step actually goes — the roofline
    comparison's numerator)

Usage:
  python tools/analyze_xplane.py profiles/llama_flash_step
  python tools/analyze_xplane.py              # every capture under profiles/
Writes PROFILES_SUMMARY.json at the repo root when run over profiles/.
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PROFILES_SUMMARY.json")


def _load_opprof():
    """The shared op taxonomy (observability/opprof.py), loaded
    standalone from its file path: one bucket scheme for TPU xplane
    captures and CPU cost-model profiles, without importing the
    paddle_tpu package (this tool must stay jax-free until a capture
    is actually parsed)."""
    import importlib.util
    path = os.path.join(REPO, "paddle_tpu", "observability", "opprof.py")
    spec = importlib.util.spec_from_file_location("_opprof_standalone",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_OPPROF = _load_opprof()


def _newest_xplane(root: str):
    files = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"),
                             recursive=True))
    return files[-1] if files else None


def _canon(name: str) -> str:
    """Collapse op instances: 'fusion.123' -> 'fusion', drop hlo ids.

    Delegates to the shared opprof rule with fold=False so
    PROFILES_SUMMARY.json `top_ops_us` keys keep their historical
    spelling; class bucketing on top comes from the same taxonomy."""
    return _OPPROF.canon_op(name, fold=False)


def analyze_capture(root: str, top_k: int = 12) -> dict:
    import jax

    path = _newest_xplane(root)
    if path is None:
        return {"capture": root, "error": "no .xplane.pb found"}
    pd = jax.profiler.ProfileData.from_file(path)
    devices = []
    for plane in pd.planes:
        pname = plane.name
        is_device = ("TPU" in pname or "GPU" in pname
                     or "PjRt" in pname or "/device:" in pname
                     or "CPU" in pname)
        if not is_device or pname.startswith("/host:metadata"):
            continue
        # pick the busiest OP line as the timeline. Callstack-sampler
        # lines (e.g. 'python') carry NESTED events whose durations sum
        # past the wall span — any such line is not an op timeline and
        # must never win the busy contest, whatever its name.
        best = None
        for line in plane.lines:
            if line.name == "python":
                continue  # the host callstack sampler, never a timeline
            evs = [(e.name, e.start_ns, e.duration_ns)
                   for e in line.events]
            busy = sum(d for _, _, d in evs)
            timed = [(s, s + d) for _, s, d in evs if d > 0]
            line_span = (max(e for _, e in timed)
                         - min(s for s, _ in timed)) if timed else 0
            # op timelines tile (+ ~% of bookkeeping overlap like 'end:'
            # markers); heavily nested durations mean a sampler line
            if line_span and busy > line_span * 1.1:
                continue
            if evs and (best is None or busy > best[0]):
                best = (busy, line.name, evs)
        if best is None:
            continue
        busy, line_name, evs = best
        starts = [s for _, s, d in evs if d > 0]
        ends = [s + d for _, s, d in evs if d > 0]
        span = (max(ends) - min(starts)) if starts else 0
        ops: dict = {}
        for name, _s, d in evs:
            ops[_canon(name)] = ops.get(_canon(name), 0) + d
        top = sorted(ops.items(), key=lambda kv: -kv[1])[:top_k]
        # NEW: self time bucketed by the shared op-class taxonomy —
        # the same classes the CPU-proxy OPPROF artifacts report, so
        # TPU capture and cost-model numbers line up bucket-for-bucket
        classes = {c: 0 for c in _OPPROF.OP_CLASSES}
        for n, d in ops.items():
            classes[_OPPROF.classify_op(n)] += d
        devices.append({
            "plane": pname, "line": line_name,
            "busy_us": round(busy / 1e3, 1),
            "span_us": round(span / 1e3, 1),
            "duty_cycle": round(busy / span, 4) if span else None,
            "bubble_ratio": round(1 - busy / span, 4) if span else None,
            "top_ops_us": [(n, round(d / 1e3, 1)) for n, d in top],
            "op_class_us": {c: round(v / 1e3, 1)
                            for c, v in classes.items() if v},
        })
    return {"capture": os.path.basename(root.rstrip("/")),
            "xplane": os.path.relpath(path, REPO), "devices": devices}


def main(argv):
    targets = argv[1:]
    write_summary = False
    if not targets:
        prof_root = os.path.join(REPO, "profiles")
        targets = sorted(
            d for d in glob.glob(os.path.join(prof_root, "*"))
            if os.path.isdir(d))
        write_summary = True
        if not targets:
            print("no captures under profiles/ — run the tpu tier first")
            return 0
    reports = []
    for t in targets:
        rep = analyze_capture(t)
        reports.append(rep)
        print(f"== {rep['capture']} ==")
        if "error" in rep:
            print("  ", rep["error"])
            continue
        for d in rep["devices"]:
            print(f"  {d['plane']} [{d['line']}]: busy {d['busy_us']}us / "
                  f"span {d['span_us']}us  duty {d['duty_cycle']}  "
                  f"bubble {d['bubble_ratio']}")
            for name, us in d["top_ops_us"][:6]:
                print(f"      {us:10.1f}us  {name}")
    if write_summary:
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(reports, f, indent=1)
            f.write("\n")
        os.replace(tmp, OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
