#!/usr/bin/env python
"""Flight-recorder black box: replay crash-surviving ring journals.

After a chaos drill (or a real crash) every rank leaves a
``flight-rank<r>.ring`` under ``PADDLE_TELEMETRY_DIR`` — including the
ranks that died with ``os._exit``. This CLI replays all surviving rings
into one wall-clock-ordered cross-rank narrative of the final moments,
with a per-rank verdict (last event; whether the rank looks like it died
mid-collective or mid-fault).

    python tools/blackbox.py postmortem --dir /tmp/telemetry
    python tools/blackbox.py postmortem --dir /tmp/telemetry --json
    python tools/blackbox.py postmortem --last-seconds 5

Exit code 0 always (forensics, not a gate); see tools/telemetry_dump.py
--fleet for the metrics/findings side of the same directory.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.observability.flight import build_postmortem  # noqa: E402


def _fmt_event(e: dict) -> str:
    extras = {k: v for k, v in e.items()
              if not k.startswith("_") and k != "kind"}
    detail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    return (f"  t={e['_t']:.6f} rank={e['_rank']} "
            f"seq={e['_seq']:<6d} {e.get('kind', '?'):<18s} {detail}")


def render_text(pm: dict) -> str:
    lines = [f"# flight-recorder postmortem: {pm['dir']}"]
    if not pm["ranks"]:
        lines.append("(no flight rings found)")
        return "\n".join(lines)
    lines.append("")
    lines.append("## per-rank verdicts")
    for rank, info in sorted(pm["ranks"].items(),
                             key=lambda kv: int(kv[0])
                             if kv[0].lstrip("-").isdigit() else 0):
        if "error" in info:
            lines.append(f"rank {rank}: UNREADABLE ({info['error']})")
            continue
        last = info["last_event"]
        verdict = "clean"
        sd = info.get("suspect_death")
        if sd is not None:
            what = sd.get("op") or sd.get("point") or sd.get("fault")
            verdict = f"SUSPECT DEATH mid-{sd['kind']}" + (
                f" ({what})" if what else "")
        elif info.get("open_collectives"):
            verdict = ("open collectives at end: "
                       f"{info['open_collectives']}")
        lines.append(
            f"rank {rank}: {info['events']} events "
            f"(epochs {info['epochs']}), last="
            f"{last.get('kind')}@t={last['_t']:.6f} -> {verdict}")
    lines.append("")
    lines.append("## cross-rank timeline (wall-clock order)")
    for e in pm["timeline"]:
        lines.append(_fmt_event(e))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="blackbox.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    pm = sub.add_parser("postmortem",
                        help="replay ring journals into a narrative")
    pm.add_argument("--dir", default=os.environ.get(
        "PADDLE_TELEMETRY_DIR"),
        help="telemetry dir holding flight-rank*.ring "
             "(default: $PADDLE_TELEMETRY_DIR)")
    pm.add_argument("--json", action="store_true",
                    help="emit the raw postmortem dict as JSON")
    pm.add_argument("--last-seconds", type=float, default=None,
                    help="only events within this window of each "
                         "rank's final event")
    args = ap.parse_args(argv)
    if not args.dir:
        ap.error("--dir required (or set PADDLE_TELEMETRY_DIR)")
    result = build_postmortem(args.dir, last_seconds=args.last_seconds)
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(render_text(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
