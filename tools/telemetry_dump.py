#!/usr/bin/env python
"""Dump the paddle_tpu telemetry registry (Prometheus text or JSONL),
the request-trace recorder (``--format chrome``), or an SLO burn-rate
summary (``--slo``).

Two modes:

  * default — run a small demo workload in-process (a ContinuousBatcher
    decode over a tiny GPT-2 plus a few hapi train steps) so the dump
    shows every instrumented subsystem populated, then render the live
    registry. This is the zero-to-metrics smoke path:

        python tools/telemetry_dump.py --format prometheus

  * --snapshot PATH — skip the workload and re-render a JSONL snapshot a
    previous run wrote (bench.py writes BENCH_TELEMETRY.jsonl; any
    process can via paddle_tpu.observability.write_jsonl).

Registries are per-process: a dump can only show series recorded in THIS
process (live mode) or captured in a snapshot file — there is no cross-
process scrape endpoint here.
"""
from __future__ import annotations

import argparse
import os
import sys

# CPU by default so the tool runs anywhere (flag through to TPU by
# exporting JAX_PLATFORMS yourself); must precede the jax import chain
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _demo_workload():
    """Touch every instrumented subsystem once: serving + training."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ContinuousBatcher
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM

    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    m = GPT2ForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(0)
    with paddle.no_grad():
        b = ContinuousBatcher(m, max_batch=2, s_max=32, compile=False)
        for s, n in ((5, 6), (9, 4), (7, 5)):
            b.submit(rng.randint(0, 128, (s,)), n)
        b.run_until_done()

        # the serving control plane: a 2-replica gateway populates the
        # gateway.* series (routing, quotas, TTFT/TPOT)
        from paddle_tpu.inference.gateway import Gateway
        gw = Gateway(policy="affinity")
        for name in ("r0", "r1"):
            gw.add_replica(name, ContinuousBatcher(
                m, max_batch=2, s_max=32, compile=False))
        for i, (s, n) in enumerate(((5, 4), (6, 4), (5, 3))):
            gw.submit(rng.randint(0, 128, (s,)), n,
                      tenant="demo", session_id=f"s{i % 2}")
        gw.run_until_done()

    from paddle_tpu import hapi, nn, optimizer
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = hapi.Model(net)
    model.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                  loss=nn.MSELoss())
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randn(16, 1).astype(np.float32)
    for _ in range(4):
        model.train_batch(x, y)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--format", choices=("prometheus", "jsonl", "chrome"),
                    default="prometheus",
                    help="chrome = the request-trace recorder as Chrome "
                         "trace-event JSON (open in chrome://tracing / "
                         "Perfetto); live mode only")
    ap.add_argument("--snapshot", metavar="PATH", default=None,
                    help="render this JSONL snapshot instead of running "
                         "the demo workload")
    ap.add_argument("--out", metavar="PATH", default=None,
                    help="write here instead of stdout")
    ap.add_argument("--prefix", metavar="DOTTED.", default=None,
                    help="only series whose name starts with this "
                         "prefix (e.g. --prefix gateway. for the "
                         "serving control plane)")
    ap.add_argument("--no-workload", action="store_true",
                    help="live mode without the demo workload (dumps "
                         "whatever this process has recorded, i.e. "
                         "nothing unless you imported + ran paddle_tpu "
                         "code first)")
    ap.add_argument("--trace-id", metavar="ID", default=None,
                    help="with --format chrome: export only this trace")
    ap.add_argument("--slo", action="store_true",
                    help="append an SLO burn-rate summary (default "
                         "gateway TTFT/TPOT objectives, polled over the "
                         "live registry) as JSON after the dump")
    ap.add_argument("--fleet", metavar="DIR", default=None,
                    help="merge the per-rank telemetry shards under DIR "
                         "(written when PADDLE_TELEMETRY_DIR is set) "
                         "into one fleet view: counters summed, "
                         "histograms merged, gauges per-rank, plus "
                         "collective skew gauges and typed straggler/"
                         "desync/missing-rank findings")
    ap.add_argument("--waterfall", metavar="RID", default=None,
                    help="render the latency waterfall + critical path "
                         "for one request (gateway gid or trace id), "
                         "from the live recorder or --fleet DIR")
    ap.add_argument("--ledger", action="store_true",
                    help="append the goodput ledger summary "
                         "(chip-seconds by tenant/rung/phase + waste "
                         "categories) built from the same spans")
    ap.add_argument("--actions", action="store_true",
                    help="with --fleet: render the auto-remediation "
                         "timeline (``remediation`` spool events the "
                         "AutoRemediator journals: decision, action, "
                         "target, triggering signal, reason), "
                         "chronological across ranks")
    ap.add_argument("--sessions", action="store_true",
                    help="with --fleet: render the durable-session "
                         "timeline (``session`` spool events: pin/"
                         "pause/publish/load/resume/release, drain "
                         "preservation, typed manifest findings), "
                         "chronological across ranks")
    ap.add_argument("--opprof", action="store_true",
                    help="render the newest OPPROF_r*.json op-level "
                         "cost artifact at the repo root (per-op-class "
                         "cost shares, gap attribution, diff vs the "
                         "previous round) — no workload, no jax")
    ap.add_argument("--locks", metavar="DIR", default=None,
                    help="render the lock-contention table (top sites "
                         "by wait/hold p99, plus any CC405/CC406 "
                         "findings) from the witness_*.json dumps a "
                         "PADDLE_LOCK_WITNESS=1 run left under DIR — "
                         "no workload, no jax")
    ap.add_argument("--prefix-stats", action="store_true",
                    help="with --fleet: append a radix prefix-cache "
                         "summary (hit/miss tokens, hit rate, "
                         "evictions, KV-aware route hits, per-tier hit "
                         "tokens, host demotion/promotion traffic and "
                         "promotion-latency p50/p99) derived from the "
                         "fleet-summed serving.prefix_* and "
                         "gateway.route.prefix_hit series")
    args = ap.parse_args(argv)

    if args.prefix_stats and not args.fleet:
        ap.error("--prefix-stats summarizes the fleet view; "
                 "use it with --fleet DIR")
    if args.actions and not args.fleet:
        ap.error("--actions renders the remediation timeline from the "
                 "per-rank spools; use it with --fleet DIR")
    if args.sessions and not args.fleet:
        ap.error("--sessions renders the durable-session timeline from "
                 "the per-rank spools; use it with --fleet DIR")

    if args.opprof:
        # the op-level cost view: artifacts only, so load opprof.py
        # standalone (stdlib-only module) and skip the jax import chain
        # entirely — and return BEFORE any other path so every existing
        # flag combination stays byte-identical
        import importlib.util
        import json
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "_opprof_standalone",
            os.path.join(repo, "paddle_tpu", "observability", "opprof.py"))
        opprof = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(opprof)
        paths = opprof.artifact_paths(repo)
        docs = [(p, opprof.load_artifact(p)) for p in paths]
        docs = [(p, d) for p, d in docs if d is not None]
        if not docs:
            sys.stderr.write("no OPPROF_r*.json artifacts at the repo "
                             "root (run bench.py, or "
                             "tools/profile_report.py for a live demo)\n")
            return 1
        path, doc = docs[-1]
        text = f"# opprof {os.path.basename(path)}\n"
        h = doc.get("headline") or {}
        text += (f"headline: {h.get('label')} [{h.get('fingerprint')}] "
                 f"top={h.get('top_class')}:{h.get('top_share')} "
                 f"recompiles={h.get('n_recompiles')}\n")
        for lbl, pd in sorted((doc.get("captures") or {}).items()):
            prof = opprof.OpProfile.from_dict(pd)
            text += f"== {lbl} [{prof.fingerprint}]\n"
            table = prof.op_class_table()
            for cls in opprof.OP_CLASSES:
                t = table[cls]
                if t["n_ops"]:
                    text += (f"  {cls:>13}: share {t['cost_share']:6.3f}"
                             f"  ({t['n_ops']} ops)\n")
        gap = doc.get("gap_attribution")
        if gap:
            text += "== gap attribution (phase x op class)\n"
            for phase, parts in gap.items():
                tops = sorted(((c, v) for c, v in parts.items() if v > 0),
                              key=lambda kv: -kv[1])[:3]
                seg = "  ".join(f"{c}={v:.4f}" for c, v in tops) or "-"
                text += (f"  {phase:>10} "
                         f"(total {sum(parts.values()):.4f}): {seg}\n")
        if len(docs) >= 2:
            prev_path, prev = docs[-2]
            d = opprof.diff(prev, doc)
            text += (f"== diff vs {os.path.basename(prev_path)}\n"
                     + json.dumps(d, indent=1) + "\n")
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.locks:
        # the lock-contention view: witness_*.json artifacts only, so no
        # paddle_tpu/jax import — early return keeps every existing flag
        # combination byte-identical (same pattern as --opprof)
        import glob
        import json
        files = ([args.locks] if os.path.isfile(args.locks) else
                 sorted(glob.glob(os.path.join(args.locks,
                                               "witness*.json"))))
        if not files:
            sys.stderr.write(f"no witness_*.json under {args.locks} "
                             "(run with PADDLE_LOCK_WITNESS=1, e.g. "
                             "tools/chaos_run.py --witness)\n")
            return 1
        rows = []   # (wait_p99, hold_p99, site, dump, wait, hold)
        findings = []
        edges = 0
        for path in files:
            try:
                with open(path) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError) as exc:
                sys.stderr.write(f"unreadable witness dump {path}: "
                                 f"{exc}\n")
                return 1
            tag = os.path.basename(path)
            edges += len(doc.get("edges", ()))
            for site, st in (doc.get("sites") or {}).items():
                w, h = st.get("wait", {}), st.get("hold", {})
                rows.append((w.get("p99", 0.0), h.get("p99", 0.0),
                             site, tag, w, h))
            for f in doc.get("findings", ()):
                findings.append((tag, f))
        rows.sort(key=lambda r: (-max(r[0], r[1]), r[2]))
        text = (f"# lock witness ({len(files)} dump(s), {len(rows)} "
                f"site(s), {edges} observed edge(s), "
                f"{len(findings)} finding(s))\n")
        text += (f"{'site':56} {'acq':>6} {'wait_p99':>10} "
                 f"{'hold_p99':>10} {'hold_max':>10}  dump\n")
        for wp, hp, site, tag, w, h in rows[:30]:
            text += (f"{site[:56]:56} {h.get('count', 0):>6} "
                     f"{wp * 1e3:>8.3f}ms {hp * 1e3:>8.3f}ms "
                     f"{h.get('max', 0.0) * 1e3:>8.3f}ms  {tag}\n")
        if len(rows) > 30:
            text += f"... {len(rows) - 30} more site(s) elided\n"
        for tag, f in findings:
            text += (f"!! [{f.get('rule', '?')}] {tag}: "
                     f"{f.get('message', '')}\n")
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        return 0

    from paddle_tpu.observability import export as _export

    if args.actions:
        # the remediation timeline: every AutoRemediator decision
        # (executed or suppressed-and-why) as journaled into the rank
        # spools, chronological across the fleet
        from paddle_tpu.observability.fleet import FleetAggregator
        agg = FleetAggregator(args.fleet)
        evs = [(e.get("t", 0.0), rank, e)
               for rank, shard in sorted(agg.shards.items())
               for e in shard.events
               if e.get("name") == "remediation"]
        evs.sort(key=lambda x: (x[0], x[1]))
        n_exec = sum(1 for _, _, e in evs
                     if e.get("decision") == "executed")
        text = (f"# remediation timeline ({len(evs)} decision(s), "
                f"{n_exec} executed)\n")
        t0 = evs[0][0] if evs else 0.0
        for t, rank, e in evs:
            text += (f"+{t - t0:8.3f}s rank{rank} "
                     f"{e.get('decision', '?'):10} "
                     f"{e.get('action', '?'):16} "
                     f"{e.get('target', '') or '-':12} "
                     f"<- {e.get('signal', '?'):24} "
                     f"| {e.get('reason', '')}\n")
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.sessions:
        # the durable-session timeline: every pin/pause/publish/load/
        # resume/release plus drain preservation and typed manifest
        # findings, as journaled into the rank spools, chronological
        # across the fleet — handled like --actions (early return) so
        # every existing flag combination stays byte-identical
        from paddle_tpu.observability.fleet import FleetAggregator
        agg = FleetAggregator(args.fleet)
        evs = [(e.get("t", 0.0), rank, e)
               for rank, shard in sorted(agg.shards.items())
               for e in shard.events
               if e.get("name") == "session"]
        evs.sort(key=lambda x: (x[0], x[1]))
        n_find = sum(1 for _, _, e in evs if e.get("op") == "finding")
        text = (f"# session timeline ({len(evs)} event(s), "
                f"{n_find} finding(s))\n")
        t0 = evs[0][0] if evs else 0.0
        for t, rank, e in evs:
            extra = " ".join(
                f"{k}={e[k]}" for k in ("replica", "blocks", "tokens",
                                        "source", "gid", "finding",
                                        "sessions", "deleted")
                if k in e)
            text += (f"+{t - t0:8.3f}s rank{rank} "
                     f"{e.get('op', '?'):14} "
                     f"{e.get('session', '') or '-':16} "
                     f"{extra}"
                     + (f" | {e['detail']}" if e.get("detail") else "")
                     + "\n")
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.waterfall is not None or args.ledger:
        # attribution views (observability.waterfall / .ledger): spans
        # come from --fleet DIR when given, else the live recorder.
        # Handled BEFORE the plain --fleet path so that path's output
        # stays byte-identical when these flags are absent.
        if args.snapshot or args.format == "chrome":
            ap.error("--waterfall/--ledger read trace spans (live "
                     "recorder or --fleet DIR), not a metrics snapshot")
        import json
        from paddle_tpu.observability.waterfall import (
            render_waterfall, waterfalls_from_fleet,
            waterfalls_from_recorder)
        if args.fleet:
            wfs = waterfalls_from_fleet(args.fleet)
        else:
            if not args.no_workload:
                _demo_workload()
            wfs = waterfalls_from_recorder()
        text = ""
        if args.waterfall is not None:
            rid = args.waterfall
            match = [w for w in wfs
                     if str(w.gid) == rid or w.trace_id == rid]
            if not match:
                sys.stderr.write(f"no trace matches rid/trace-id "
                                 f"{rid!r} ({len(wfs)} trace(s) "
                                 f"available)\n")
                return 1
            text += "\n\n".join(render_waterfall(w)
                                for w in match) + "\n"
        if args.ledger:
            from paddle_tpu.observability.ledger import \
                ledger_from_waterfalls
            led = ledger_from_waterfalls(wfs)
            led.publish()
            text += ("# goodput ledger\n"
                     + json.dumps(led.summary(), indent=2) + "\n")
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.fleet:
        if args.snapshot or args.slo or args.format == "chrome":
            ap.error("--fleet renders rank shards; it composes only "
                     "with --format prometheus/jsonl and --prefix")
        import json
        from paddle_tpu.observability.fleet import FleetAggregator
        agg = FleetAggregator(args.fleet)
        series = agg.fleet_series()
        if args.prefix:
            series = [s for s in series
                      if s["name"].startswith(args.prefix)]
        if args.format == "prometheus":
            text = _export.render_prometheus(series=series)
        else:
            text = "".join(json.dumps(s) + "\n" for s in series)
        text += (f"# fleet ranks {agg.ranks()}\n")
        for f in agg.findings():
            text += "# fleet finding " + json.dumps(f.to_dict()) + "\n"
        if args.prefix_stats:
            sums = {}
            by_tier = {}
            promo_q = {}
            gauges = {}
            for s in agg.fleet_series():
                if s.get("type") == "counter":
                    sums[s["name"]] = sums.get(s["name"], 0) \
                        + s.get("value", 0)
                    if s["name"] == "serving.prefix_tier_hit_tokens":
                        t = (s.get("labels") or {}).get("tier", "?")
                        by_tier[t] = by_tier.get(t, 0) \
                            + s.get("value", 0)
                elif s.get("type") == "gauge":
                    gauges[s["name"]] = gauges.get(s["name"], 0) \
                        + s.get("value", 0)
                elif s.get("type") == "histogram" and \
                        s["name"] == "serving.prefix_promotion_seconds":
                    promo_q = s.get("quantiles") or {}
            hit = sums.get("serving.prefix_hit_tokens", 0)
            miss = sums.get("serving.prefix_miss_tokens", 0)
            stats = {
                "hit_tokens": hit,
                "miss_tokens": miss,
                "hit_rate": round(hit / max(hit + miss, 1), 4),
                "evictions": sums.get("serving.prefix_evictions", 0),
                "route_prefix_hits": sums.get(
                    "gateway.route.prefix_hit", 0),
            }
            if by_tier:
                # tiered KV columns only when a host tier reported:
                # untiered fleets keep the legacy line byte-identical
                stats["hit_tokens_by_tier"] = dict(sorted(
                    by_tier.items()))
                stats["promotions"] = sums.get(
                    "serving.prefix_promotions", 0)
                stats["promotion_failures"] = sums.get(
                    "serving.prefix_promotion_failures", 0)
                stats["demoted_bytes"] = sums.get(
                    "serving.prefix_demoted_bytes", 0)
                for q in ("p50", "p99"):
                    v = promo_q.get(q)
                    if v is not None:
                        stats[f"promotion_latency_{q}_ms"] = round(
                            v * 1e3, 3)
            blob = sums.get("serving.prefix_spill_blob_bytes", 0)
            if blob:
                # quantized-spill columns only when spill traffic
                # reported the new counters: legacy fleets (and runs
                # with no demotions) keep the line byte-identical.
                # compression = what the demoted chains WOULD cost raw
                # over what they cost as stored (≈3.9x with
                # tier_quant='int8' + per-head scales, 1.0 untouched)
                raw = sums.get("serving.prefix_spill_raw_bytes", 0)
                stats["spill_raw_bytes"] = raw
                stats["spill_blob_bytes"] = blob
                stats["spill_compression"] = round(raw / max(blob, 1), 2)
                if "serving.kv_host_bytes" in gauges:
                    stats["host_blob_bytes"] = gauges[
                        "serving.kv_host_bytes"]
            text += "# fleet prefix-stats " + json.dumps(stats) + "\n"
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.format == "chrome":
        if args.snapshot:
            ap.error("--format chrome reads the live trace recorder; "
                     "it cannot render a metrics --snapshot")
        if not args.no_workload:
            _demo_workload()
        import json
        from paddle_tpu.observability import get_recorder
        doc = get_recorder().to_chrome(args.trace_id)
        text = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text + "\n")
        return 0

    slo_monitor = None
    if args.snapshot:
        if args.slo:
            ap.error("--slo evaluates the live registry; it cannot "
                     "render a metrics --snapshot")
        series = _export.load_jsonl(args.snapshot)
    else:
        if args.slo:
            # first poll BEFORE the workload so the window delta covers
            # the demo traffic
            from paddle_tpu.observability import (SLOMonitor,
                                                  default_gateway_slos)
            slo_monitor = SLOMonitor(default_gateway_slos())
            slo_monitor.poll()
        if not args.no_workload:
            _demo_workload()
        if slo_monitor is not None:
            slo_monitor.poll()
        series = _export.snapshot_series()

    if args.prefix:
        series = [s for s in series if s["name"].startswith(args.prefix)]

    if args.format == "prometheus":
        text = _export.render_prometheus(series=series)
        if not args.snapshot:
            # drops are silent in the series themselves; surface them
            from paddle_tpu.observability import get_recorder
            dropped = get_recorder().dropped
            if dropped > 0:
                text += (f"# trace.dropped_spans {dropped} "
                         f"(capacity {get_recorder().capacity}; raise "
                         f"PADDLE_TRACE_CAP or export more often)\n")
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
    else:
        if args.out:
            _export.write_jsonl(args.out, series=series)
        else:
            import json
            for s in series:
                sys.stdout.write(json.dumps(s) + "\n")
    if slo_monitor is not None:
        import json
        sys.stdout.write("# slo summary\n")
        sys.stdout.write(json.dumps(slo_monitor.summary(), indent=2)
                         + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
