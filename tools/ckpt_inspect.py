#!/usr/bin/env python
"""Offline checkpoint-root inspector: manifest <-> shards <-> acks.

    python tools/ckpt_inspect.py /path/to/ckpt_root
    python tools/ckpt_inspect.py /path/to/ckpt_root --json
    python tools/ckpt_inspect.py --selftest

Walks every ``step_*`` directory under the root and cross-checks the
two-phase sharded layout the resilience ``ShardedCheckpointManager``
publishes: the COMMITTED marker, MANIFEST.json, every per-rank
``SHARD_OK.rankNNNNN`` ack the manifest lists, every shard file, and the
crc32 of every chunk's raw bytes against the manifest's recorded
checksum. Legacy (single-file ``CheckpointManager``) steps are reported
by their COMMITTED marker only. Exit codes: 0 every step is sound, 2 at
least one step is torn/uncommitted/corrupt, 1 usage or I/O error.

Deliberately stdlib-only (zipfile + a hand-rolled .npy header parse
instead of numpy): this is the tool an operator runs on a machine that
may have nothing but a Python interpreter and the checkpoint volume,
and the lint lane imports it with the same constraint.
"""
from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import zipfile
import zlib

COMMITTED = "COMMITTED"
MANIFEST = "MANIFEST.json"
ACK_PREFIX = "SHARD_OK.rank"


def npy_payload(raw: bytes) -> bytes:
    """The array bytes of a serialized .npy member (header skipped).

    For the C-contiguous arrays ``np.savez`` writes, the payload after
    the header IS ``arr.tobytes()`` — exactly what the saver's
    ``chunk_crc`` hashed."""
    if raw[:6] != b"\x93NUMPY":
        raise ValueError("not an npy member (bad magic)")
    major = raw[6]
    if major == 1:
        (hlen,) = struct.unpack_from("<H", raw, 8)
        start = 10 + hlen
    else:
        (hlen,) = struct.unpack_from("<I", raw, 8)
        start = 12 + hlen
    return raw[start:]


def inspect_step(path: str) -> dict:
    """One step dir -> {step, kind, ok, reason, acks, chunks, bytes}."""
    out = {"dir": path, "step": None, "kind": "legacy", "ok": True,
           "reason": "", "acks": 0, "chunks": 0, "bytes": 0}

    def bad(reason):
        out["ok"] = False
        out["reason"] = reason
        return out

    committed = os.path.join(path, COMMITTED)
    manifest = os.path.join(path, MANIFEST)
    sharded_debris = any(
        n.startswith(ACK_PREFIX) or n.startswith("shard-rank")
        for n in os.listdir(path))
    if sharded_debris or os.path.exists(manifest):
        out["kind"] = "sharded"
    if not os.path.exists(committed):
        return bad("uncommitted: no COMMITTED marker"
                   + (" (torn sharded save)" if out["kind"] == "sharded"
                      else ""))
    try:
        with open(committed) as f:
            out["step"] = json.load(f).get("step")
    except (OSError, ValueError) as e:
        return bad(f"unreadable COMMITTED marker: {e}")
    if out["kind"] == "legacy":
        return out

    if not os.path.exists(manifest):
        return bad("committed but MANIFEST.json is missing")
    try:
        with open(manifest) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return bad(f"unreadable MANIFEST.json: {e}")
    for rank in range(int(man.get("world_size", 1))):
        ack = os.path.join(path, f"{ACK_PREFIX}{rank:05d}")
        if not os.path.exists(ack):
            return bad(f"missing ack {ACK_PREFIX}{rank:05d}")
        out["acks"] += 1

    # one pass per shard file: open the zip once, then CRC every chunk
    # the manifest says lives in it
    by_file: dict = {}
    for key, entry in man.get("tensors", {}).items():
        for ch in entry.get("chunks", []):
            by_file.setdefault(ch["file"], []).append((key, ch))
    for fname, chunks in sorted(by_file.items()):
        fpath = os.path.join(path, fname)
        if not os.path.exists(fpath):
            return bad(f"missing shard file {fname!r}")
        try:
            zf = zipfile.ZipFile(fpath)
        except (OSError, zipfile.BadZipFile) as e:
            return bad(f"unreadable shard file {fname!r}: {e}")
        with zf:
            names = set(zf.namelist())
            for key, ch in chunks:
                member = ch["cid"] + ".npy"
                if member not in names:
                    return bad(f"shard member {ch['cid']!r} missing "
                               f"from {fname!r}")
                try:
                    payload = npy_payload(zf.read(member))
                except (ValueError, zipfile.BadZipFile) as e:
                    return bad(f"corrupt member {ch['cid']!r} in "
                               f"{fname!r}: {e}")
                if zlib.crc32(payload) != int(ch["crc"]):
                    return bad(f"checksum mismatch for {ch['cid']!r} "
                               f"({key}) in {fname!r}")
                out["chunks"] += 1
                out["bytes"] += len(payload)
    return out


def inspect_root(root: str) -> dict:
    steps = sorted(d for d in os.listdir(root)
                   if d.startswith("step_")
                   and os.path.isdir(os.path.join(root, d)))
    reports = [inspect_step(os.path.join(root, d)) for d in steps]
    return {"root": root,
            "steps": reports,
            "ok": all(r["ok"] for r in reports),
            "latest_sound": next((r["step"] for r in reversed(reports)
                                  if r["ok"]), None)}


def print_table(report: dict) -> None:
    print(f"checkpoint root: {report['root']}")
    if not report["steps"]:
        print("  (no step directories)")
        return
    hdr = f"  {'dir':24} {'kind':8} {'acks':>4} {'chunks':>6} " \
          f"{'bytes':>10}  status"
    print(hdr)
    for r in report["steps"]:
        status = "OK" if r["ok"] else f"BAD: {r['reason']}"
        print(f"  {os.path.basename(r['dir']):24} {r['kind']:8} "
              f"{r['acks']:>4} {r['chunks']:>6} {r['bytes']:>10}  "
              f"{status}")
    print(f"  latest sound step: {report['latest_sound']}")


def _selftest() -> int:
    """Build a tiny synthetic root (one sound sharded step, one torn)
    with nothing but the stdlib, then check the verdicts."""
    import io
    import tempfile

    def npy_bytes(payload: bytes, shape) -> bytes:
        header = ("{'descr': '<f4', 'fortran_order': False, "
                  f"'shape': {tuple(shape)!r}, }}").encode()
        pad = 64 - ((10 + len(header) + 1) % 64)
        header += b" " * pad + b"\n"
        return (b"\x93NUMPY\x01\x00" + struct.pack("<H", len(header))
                + header + payload)

    with tempfile.TemporaryDirectory(prefix="ckpt_inspect_self_") as root:
        payload = struct.pack("<4f", 1.0, 2.0, 3.0, 4.0)
        cid = "w@0_0"
        for step, sound in ((1, True), (2, False)):
            d = os.path.join(root, f"step_{step:012d}")
            os.makedirs(d)
            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w") as zf:
                zf.writestr(cid + ".npy", npy_bytes(payload, (2, 2)))
            shard = "shard-rank00000-000.npz"
            with open(os.path.join(d, shard), "wb") as f:
                f.write(buf.getvalue())
            if not sound:
                continue  # torn: shard written, never published
            man = {"step": step, "world_size": 1,
                   "tensors": {"w": {"chunks": [
                       {"file": shard, "cid": cid, "offset": [0, 0],
                        "shape": [2, 2], "crc": zlib.crc32(payload)}]}}}
            with open(os.path.join(d, MANIFEST), "w") as f:
                json.dump(man, f)
            with open(os.path.join(d, f"{ACK_PREFIX}00000"), "w") as f:
                json.dump({"rank": 0, "step": step}, f)
            with open(os.path.join(d, COMMITTED), "w") as f:
                json.dump({"step": step}, f)
        rep = inspect_root(root)
        s1, s2 = rep["steps"]
        assert s1["ok"] and s1["chunks"] == 1, s1
        assert not s2["ok"] and "torn" in s2["reason"], s2
        assert rep["latest_sound"] == 1, rep
        # now corrupt the sound step's payload and re-verify detection
        shard_path = os.path.join(root, "step_000000000001",
                                  "shard-rank00000-000.npz")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as zf:
            zf.writestr(cid + ".npy", npy_bytes(payload[:-4] + b"\0\0\0\0",
                                                (2, 2)))
        with open(shard_path, "wb") as f:
            f.write(buf.getvalue())
        bad = inspect_step(os.path.join(root, "step_000000000001"))
        assert not bad["ok"] and "checksum" in bad["reason"], bad
    print("ckpt_inspect selftest OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", help="checkpoint root directory")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="validate the inspector against a synthetic "
                         "root and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.root:
        ap.error("root is required (or --selftest)")
    if not os.path.isdir(args.root):
        print(f"error: {args.root!r} is not a directory", file=sys.stderr)
        return 1
    report = inspect_root(args.root)
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        print_table(report)
    return 0 if report["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
