#!/usr/bin/env python
"""Bench-trajectory regression gate over the committed ``BENCH_*.json``.

The driver appends one ``BENCH_rNN.json`` per round (a wrapper
``{n, cmd, rc, tail, parsed}`` whose ``parsed`` field holds the bench
line bench.py printed). This tool reads the ordered history, separates
real-TPU points from CPU-proxy points (``detail.tpu`` — the two run on
different hardware and must never be compared against each other), and
fails loudly when the NEWEST point of a series regresses below a
tolerance band fit to its own recent history:

    lower_bound = (1 - tolerance) * median(previous k points)

Median over a trailing window (not the single previous point) so one
noisy round neither hides a real regression nor trips a false one; a
linear trend fit is reported for context but never gates (trend is a
narrative, the band is the contract). Records with ``rc != 0`` or an
unparsable line (e.g. a timed-out round) are skipped with a note — a
wedged round is not a regression.

CI wiring: ``python tools/bench_guard.py --check`` exits 0 (pass, or
nothing to check) / 1 (regression), printing the verdict per series.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

DEFAULT_TOLERANCE = 0.10
DEFAULT_WINDOW = 4
DEFAULT_RELAY_WINDOW = 4


def discover(dirpath: str, prefix: str = "BENCH_r") -> List[dict]:
    """Ordered bench records: ``{prefix}*.json`` sorted by round number.
    Each returned dict is the PARSED bench line plus ``_round``/``_file``
    bookkeeping; unusable rounds appear with ``_skip`` set (reason).
    The default prefix is the train lane; the gateway lane lives in
    ``BENCH_GATEWAY_r*.json`` (bench_gateway.py writes it), the
    multichip lane in ``MULTICHIP_r*.json`` (bench_multichip.py), the
    KV-tier churn lane in ``BENCH_PREFIX_r*.json``
    (bench_prefix_churn.py), the self-heal traffic lane in
    ``BENCH_TRAFFIC_r*.json`` (bench_selfheal.py), the durable-session
    resume lane in ``BENCH_SESSION_r*.json`` (bench_session.py), the
    serving-quantization lane in ``BENCH_QUANT_r*.json``
    (bench_quant.py), and the op-profile lane in ``OPPROF_r*.json``
    (opprof cost artifacts,
    synthesized into inverse drift series directly in ``run_check``) —
    all pulled in by ``run_check`` with their own prefixes. The globs are disjoint, so the relay gate
    (train-lane-only by construction) never sees the other lanes'
    rounds, and pre-lane MULTICHIP artifacts (raw dry-run wrappers
    without a parsed bench line) skip cleanly."""
    out: List[dict] = []
    rx = re.compile(re.escape(prefix) + r"(\d+)\.json$")
    for path in sorted(glob.glob(os.path.join(dirpath,
                                              prefix + "*.json"))):
        m = rx.search(os.path.basename(path))
        if not m:
            continue
        rnd = int(m.group(1))
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError) as e:
            out.append({"_round": rnd, "_file": path,
                        "_skip": f"unreadable: {e}"})
            continue
        # driver wrapper {n, cmd, rc, parsed} or a bare bench line (test
        # fixtures / manual runs)
        if "parsed" in raw or "rc" in raw:
            rc = raw.get("rc", 0)
            parsed = raw.get("parsed")
            if rc != 0 or not isinstance(parsed, dict):
                out.append({"_round": rnd, "_file": path,
                            "_skip": f"rc={rc}, parsed="
                                     f"{'ok' if parsed else parsed}"})
                continue
            rec = dict(parsed)
        elif "value" in raw:
            rec = dict(raw)
        else:
            out.append({"_round": rnd, "_file": path,
                        "_skip": "no parsed bench line"})
            continue
        if not isinstance(rec.get("value"), (int, float)):
            out.append({"_round": rnd, "_file": path,
                        "_skip": "non-numeric value"})
            continue
        rec["_round"] = rnd
        rec["_file"] = path
        out.append(rec)
    return out


def split_series(records: List[dict]) -> dict:
    """Group usable points by (metric, hardware): CPU-proxy and TPU
    points form separate series."""
    series: dict = {}
    for r in records:
        if "_skip" in r:
            continue
        hw = "tpu" if r.get("detail", {}).get("tpu") else "cpu"
        metric = r.get("metric", "unknown")
        lane = r.get("_lane")
        key = (f"{lane}:{metric}" if lane else metric, hw)
        series.setdefault(key, []).append(r)
    return series


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _trend(points: List[float]) -> Optional[float]:
    """Least-squares slope per round (info only)."""
    n = len(points)
    if n < 2:
        return None
    xbar = (n - 1) / 2.0
    ybar = sum(points) / n
    num = sum((i - xbar) * (y - ybar) for i, y in enumerate(points))
    den = sum((i - xbar) ** 2 for i in range(n))
    return num / den if den else None


def check_series(points: List[dict], tolerance: float,
                 window: int) -> dict:
    """Gate the NEWEST point against median(previous ``window``)."""
    values = [float(p["value"]) for p in points]
    result = {
        "n_points": len(values),
        "values": values,
        "rounds": [p["_round"] for p in points],
        "latest": values[-1] if values else None,
        "trend_per_round": _trend(values),
        "status": "pass",
    }
    if len(values) < 2:
        result["status"] = "insufficient_history"
        return result
    prior = values[:-1][-window:]
    baseline = _median(prior)
    bound = (1.0 - tolerance) * baseline
    result.update(baseline=baseline, lower_bound=bound)
    if values[-1] < bound:
        result["status"] = "regression"
        result["drop_frac"] = 1.0 - values[-1] / baseline
    return result


def run_check(dirpath: str, tolerance: float = DEFAULT_TOLERANCE,
              window: int = DEFAULT_WINDOW) -> dict:
    records = discover(dirpath)
    gw_records = discover(dirpath, prefix="BENCH_GATEWAY_r")
    for r in gw_records:
        r["_lane"] = "gateway"
    mc_records = discover(dirpath, prefix="MULTICHIP_r")
    for r in mc_records:
        r["_lane"] = "multichip"
    # synthesize the goodput series from the gateway lane's embedded
    # ledger (detail.goodput_frac_cache_on, written by bench_gateway
    # since round 15): goodput regressions gate exactly like
    # throughput. Older artifacts without the field simply contribute
    # no point (insufficient_history until two rounds carry it).
    goodput_records = []
    for r in gw_records:
        if "_skip" in r:
            continue
        gp = (r.get("detail") or {}).get("goodput_frac_cache_on")
        if isinstance(gp, (int, float)):
            goodput_records.append({
                "metric": "gateway_goodput_frac", "value": float(gp),
                "unit": "frac",
                "detail": {"tpu": (r.get("detail") or {}).get("tpu")},
                "_round": r["_round"], "_file": r["_file"],
                "_lane": "gateway"})
    px_records = discover(dirpath, prefix="BENCH_PREFIX_r")
    for r in px_records:
        r["_lane"] = "prefix"
    # the churn bench's headline value is the TIERED durable hit rate;
    # promotion latency gates as an INVERSE series (promotions/s from
    # detail.promotion_latency_p99_ms) because the band is a lower
    # bound — a latency blowup shows up as the rate collapsing.
    promo_records = []
    for r in px_records:
        if "_skip" in r:
            continue
        p99 = (r.get("detail") or {}).get("promotion_latency_p99_ms")
        if isinstance(p99, (int, float)) and p99 > 0:
            promo_records.append({
                "metric": "prefix_promotion_p99_rate",
                "value": 1000.0 / float(p99), "unit": "promotions/s",
                "detail": {"tpu": (r.get("detail") or {}).get("tpu")},
                "_round": r["_round"], "_file": r["_file"],
                "_lane": "prefix"})
    tr_records = discover(dirpath, prefix="BENCH_TRAFFIC_r")
    for r in tr_records:
        r["_lane"] = "traffic"
    # the self-heal bench's headline value is remediation-on
    # goodput_frac; recovery time gates as an INVERSE series
    # (recoveries per 100 steps from detail.recovery_steps_on) for the
    # same reason as promotion latency — the band is a lower bound, so
    # slower recovery shows up as the rate collapsing.
    recov_records = []
    for r in tr_records:
        if "_skip" in r:
            continue
        rs = (r.get("detail") or {}).get("recovery_steps_on")
        if isinstance(rs, (int, float)) and rs >= 0:
            recov_records.append({
                "metric": "traffic_recovery_rate",
                "value": 100.0 / max(float(rs), 1.0),
                "unit": "recoveries/100steps",
                "detail": {"tpu": (r.get("detail") or {}).get("tpu")},
                "_round": r["_round"], "_file": r["_file"],
                "_lane": "traffic"})
    se_records = discover(dirpath, prefix="BENCH_SESSION_r")
    for r in se_records:
        r["_lane"] = "session"
    # the session bench's headline value is resume goodput (resumed
    # tokens/s through the pipelined promotion stream); time-to-resume
    # gates as an INVERSE series (resumes/s from
    # detail.time_to_resume_ms) because the band is a lower bound — a
    # resume-latency blowup shows up as the rate collapsing.
    ttr_records = []
    for r in se_records:
        if "_skip" in r:
            continue
        ttr = (r.get("detail") or {}).get("time_to_resume_ms")
        if isinstance(ttr, (int, float)) and ttr > 0:
            ttr_records.append({
                "metric": "session_resume_rate",
                "value": 1000.0 / float(ttr), "unit": "resumes/s",
                "detail": {"tpu": (r.get("detail") or {}).get("tpu")},
                "_round": r["_round"], "_file": r["_file"],
                "_lane": "session"})
    qt_records = discover(dirpath, prefix="BENCH_QUANT_r")
    for r in qt_records:
        r["_lane"] = "quant"
    # the quant bench's headline value is int8-weights decode tokens/s;
    # the greedy token-match rate vs the fp arm gates as a SECOND series
    # (detail.token_match_rate) so a quantizer quality regression fails
    # as loudly as a speed one. The band is a lower bound, which is the
    # right direction for a match rate. Driver dry-run wrappers (rc != 0
    # or no parsed line) are already ``_skip`` records from discover and
    # contribute no point.
    match_records = []
    for r in qt_records:
        if "_skip" in r:
            continue
        tm = (r.get("detail") or {}).get("token_match_rate")
        if isinstance(tm, (int, float)):
            match_records.append({
                "metric": "quant_token_match_rate", "value": float(tm),
                "unit": "frac",
                "detail": {"tpu": (r.get("detail") or {}).get("tpu")},
                "_round": r["_round"], "_file": r["_file"],
                "_lane": "quant"})
    # op-level profile lane: OPPROF_r*.json (opprof.write_artifact —
    # bench.py emits one per run). These are cost artifacts, not bench
    # lines, so the series are synthesized here. The band is a LOWER
    # bound, so both drift signals gate as inverse series: the top
    # op-class cost share as HEADROOM (1 - share: a fusion regression
    # concentrating cost into one class collapses the headroom) and
    # the recompile count as 1/(1+n) (a recompile storm collapses the
    # health). Driver dry-run wrappers ({n, cmd, rc} without a
    # `captures` map) skip cleanly like pre-lane MULTICHIP rounds.
    opp_records = []
    opp_rx = re.compile(r"OPPROF_r(\d+)\.json$")
    for path in sorted(glob.glob(os.path.join(dirpath,
                                              "OPPROF_r*.json"))):
        m = opp_rx.search(os.path.basename(path))
        if not m:
            continue
        rnd = int(m.group(1))
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "captures" not in doc:
            continue  # dry-run wrapper, not an opprof artifact
        h = doc.get("headline") or {}
        det = {"tpu": bool(doc.get("tpu"))}
        share = h.get("top_share")
        if isinstance(share, (int, float)):
            opp_records.append({
                "metric": "opprof_top_share_headroom",
                "value": max(0.0, 1.0 - float(share)), "unit": "frac",
                "detail": det, "_round": rnd, "_file": path,
                "_lane": "opprof"})
        nrec = h.get("n_recompiles")
        if isinstance(nrec, (int, float)):
            opp_records.append({
                "metric": "opprof_recompile_health",
                "value": 1.0 / (1.0 + float(nrec)), "unit": "frac",
                "detail": det, "_round": rnd, "_file": path,
                "_lane": "opprof"})
    records = (records + gw_records + mc_records + goodput_records
               + px_records + promo_records + tr_records
               + recov_records + se_records + ttr_records
               + qt_records + match_records + opp_records)
    report = {
        "dir": dirpath,
        "tolerance": tolerance,
        "window": window,
        "skipped": [{"round": r["_round"],
                     "lane": r.get("_lane", "train"),
                     "reason": r["_skip"]}
                    for r in records if "_skip" in r],
        "series": {},
        "status": "pass",
    }
    series = split_series(records)
    if not series:
        report["status"] = "no_history"
        return report
    for (metric, hw), pts in sorted(series.items()):
        res = check_series(pts, tolerance, window)
        report["series"][f"{metric}/{hw}"] = res
        if res["status"] == "regression":
            report["status"] = "regression"
    return report


def _relay_state(rec: dict) -> str:
    """One round's TPU-relay verdict. bench.py ≥ round 6 stamps a
    top-level ``relay`` field; older artifacts are derived from
    ``detail`` (tpu=true → ok, a fallback note → unreachable); rounds
    that produced no usable bench line count as ``round_failed``."""
    if "_skip" in rec:
        return "round_failed"
    relay = rec.get("relay")
    if isinstance(relay, str) and relay:
        return relay
    det = rec.get("detail") or {}
    if det.get("tpu"):
        return "ok"
    if det.get("fallback"):
        return "unreachable"
    return "unknown"


def check_relay(dirpath: str,
                window: int = DEFAULT_RELAY_WINDOW) -> dict:
    """Fail when the last ``window`` rounds ALL ran without the TPU
    relay (relay != "ok") — CPU-fallback rounds must not silently
    accumulate into a fake trajectory."""
    records = discover(dirpath)
    states = [{"round": r["_round"], "relay": _relay_state(r)}
              for r in records]
    ok_rounds = [s["round"] for s in states if s["relay"] == "ok"]
    report = {
        "dir": dirpath,
        "window": window,
        "rounds": states,
        "last_ok_round": ok_rounds[-1] if ok_rounds else None,
        "status": "pass",
    }
    if not states:
        report["status"] = "no_history"
        return report
    tail = states[-window:]
    if len(tail) >= window and all(s["relay"] != "ok" for s in tail):
        report["status"] = "relay_wedged"
        report["tail"] = tail
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench-trajectory regression gate")
    ap.add_argument("--check", action="store_true",
                    help="gate: exit 1 on regression (default prints "
                         "the report without gating)")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="allowed drop below the trailing median "
                         "(default 0.10)")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing points in the median baseline "
                         "(default 4)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--relay", action="store_true",
                    help="gate the TPU relay instead of the trajectory: "
                         "exit 1 when the last --relay-window rounds "
                         "ALL report relay != ok (wedged relay "
                         "accumulating CPU-fallback rounds)")
    ap.add_argument("--relay-window", type=int,
                    default=DEFAULT_RELAY_WINDOW,
                    help="consecutive not-ok rounds that trip --relay "
                         f"(default {DEFAULT_RELAY_WINDOW})")
    args = ap.parse_args(argv)

    if args.relay:
        report = check_relay(args.dir, window=args.relay_window)
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            trend = " ".join(f"r{s['round']:02d}={s['relay']}"
                             for s in report["rounds"])
            print(f"  relay trend: {trend or '(no history)'}")
            last_ok = report["last_ok_round"]
            print(f"  last ok round: "
                  f"{'r%02d' % last_ok if last_ok is not None else 'never'}")
            print(f"bench_guard --relay: {report['status'].upper()} "
                  f"(window {report['window']}, dir {report['dir']})")
        return 1 if report["status"] == "relay_wedged" else 0

    report = run_check(args.dir, tolerance=args.tolerance,
                       window=args.window)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for s in report["skipped"]:
            print(f"  skip r{s['round']:02d}: {s['reason']}")
        for key, res in report["series"].items():
            line = (f"{key}: {res['n_points']} point(s), "
                    f"latest={res['latest']}")
            if "baseline" in res:
                line += (f", baseline(median{args.window})="
                         f"{res['baseline']:.2f}, "
                         f"bound={res['lower_bound']:.2f}")
            if res["trend_per_round"] is not None:
                line += f", trend={res['trend_per_round']:+.2f}/round"
            print(f"  {line} -> {res['status'].upper()}")
        print(f"bench_guard: {report['status'].upper()} "
              f"(tolerance {args.tolerance:.0%}, dir {report['dir']})")
    if args.check and report["status"] == "regression":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
