#!/usr/bin/env python
"""Chaos drill runner: run a small workload under an injected fault
scenario and ASSERT the recovery behavior, end to end, on CPU.

    python tools/chaos_run.py --list
    python tools/chaos_run.py checkpoint
    python tools/chaos_run.py train --scenario "seed=3; train.step:nan_grad:count=2"
    python tools/chaos_run.py serve
    python tools/chaos_run.py all

Each mode arms a scenario (its default or --scenario / $PADDLE_CHAOS),
drives the subsystem through the fault, and exits nonzero unless the
system RECOVERED — a torn checkpoint save must leave the previous step
bit-identically restorable, a NaN-poisoned train loop must finish with
the bad steps skipped and counted, and an overloaded serving queue must
reject with typed errors while completing the admitted work. The same
drills run under pytest as ``pytest -m chaos``; this CLI is the
operational (cron/incident-rehearsal) entry point and prints the fault
and recovery telemetry the observability registry collected.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the elastic drill saves on a 2x2 (fsdp, tensor) mesh; give the CPU
# backend enough virtual devices before jax initializes
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_SCENARIOS = {
    "checkpoint": ("seed=0; checkpoint.write:torn_write:offset=64,"
                   "after=1,count=1"),
    "ckpt_elastic": ("seed=0; checkpoint.publish:torn_write:offset=32,"
                     "count=1"),
    "train": "seed=0; train.step:nan_grad:after=1,count=2",
    "serve": "seed=0; serving.step:transient_error:count=2",
    "selfheal": ("seed=0; gateway.step.r1:delay:delay_s=0.4,"
                 "after=1,count=10000"),
}


def _drill_checkpoint(scenario: str) -> str:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.resilience import (CheckpointManager, TornWrite,
                                       arm_scenario, disarm)

    with tempfile.TemporaryDirectory(prefix="chaos_ckpt_") as root:
        mgr = CheckpointManager(root, keep_last=3)
        golden = {"w": paddle.to_tensor(
            np.arange(24, dtype=np.float32).reshape(4, 6))}
        mgr.save(golden, step=1)

        arm_scenario(scenario)
        torn = False
        try:
            mgr.save({"w": paddle.to_tensor(
                np.full((4, 6), -1, np.float32))}, step=2)
        except TornWrite as exc:
            torn = True
            print(f"  injected: {exc}")
        finally:
            disarm()
        assert torn, "scenario did not tear the save — nothing was drilled"
        assert mgr.steps() == [1], "a torn save published a step dir"

        target = {"w": paddle.zeros([4, 6])}
        step = mgr.restore_latest(target)
        assert step == 1, f"restore_latest -> {step}, want 1"
        np.testing.assert_array_equal(target["w"].numpy(),
                                      golden["w"].numpy())
    return "torn save at an arbitrary offset; prior step restored bit-exact"


def _drill_ckpt_elastic(scenario: str) -> str:
    """Two-phase sharded save torn at the publish seam, then an ELASTIC
    restore on a different mesh: the torn step must never show a
    COMMITTED marker, restore_latest must fall back to the previous
    committed step with a typed finding, continuation must be bitwise
    on the reference trajectory, and ckpt_inspect must flag the torn
    step with a nonzero verdict."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.mesh import MeshRuntime
    from paddle_tpu.hapi import Model
    from paddle_tpu.resilience import (ShardedCheckpointManager, TornWrite,
                                       arm_scenario, disarm)
    import ckpt_inspect

    def build(plan):
        paddle.seed(7)
        m = Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                nn.Linear(16, 2)))
        m.prepare(optimizer=optimizer.AdamW(learning_rate=1e-2,
                                            parameters=m.parameters()),
                  loss=nn.CrossEntropyLoss(), jit=True, plan=plan)
        return m

    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32)
    y = rng.randint(0, 2, (4,)).astype(np.int64)

    def steps(m, n):
        return [float(np.asarray(m.train_batch([x], [y])[0]))
                for _ in range(n)]

    rt_save = MeshRuntime({"data": 1, "fsdp": 2, "tensor": 2})
    reference = steps(build(rt_save.train_plan(budget_gib=16.0)), 4)

    with tempfile.TemporaryDirectory(prefix="chaos_ckpt_elastic_") as root:
        m = build(rt_save.train_plan(budget_gib=16.0))
        before = steps(m, 2)
        mgr = ShardedCheckpointManager(root, runtime=rt_save, ack_timeout=5)
        m.save_checkpoint(mgr, step=2)

        arm_scenario(scenario)
        torn = False
        try:
            m.save_checkpoint(mgr, step=3)
        except TornWrite as exc:
            torn = True
            print(f"  injected: {exc}")
        finally:
            disarm()
        assert torn, "scenario did not tear the publish — nothing drilled"
        torn_dir = os.path.join(root, "step_000000000003")
        assert os.path.isdir(torn_dir) and not os.path.exists(
            os.path.join(torn_dir, "COMMITTED")), \
            "a torn publish left a COMMITTED marker"

        report = ckpt_inspect.inspect_root(root)
        assert not report["ok"] and report["latest_sound"] == 2, report

        # elastic restore: same state, DIFFERENT mesh (1x4)
        rt_new = MeshRuntime({"data": 1, "fsdp": 1, "tensor": 4})
        m2 = build(rt_new.train_plan(budget_gib=16.0))
        mgr2 = ShardedCheckpointManager(root, runtime=rt_new, ack_timeout=5)
        step = m2.resume_from(mgr2, runtime=rt_new)
        assert step == 2, f"restore fell back to {step}, want 2"
        kinds = [f.kind for f in mgr2.findings]
        assert "torn_step" in kinds or "uncommitted" in kinds, \
            f"no typed finding for the torn step (got {kinds})"
        after = steps(m2, 2)
        assert before + after == reference, \
            (f"rescaled continuation diverged: {before + after} "
             f"vs {reference}")
    return (f"publish torn at step 3, inspector latest_sound=2, "
            f"findings {kinds}, 2x2 -> 1x4 restore continued bitwise")


def _drill_train(scenario: str) -> str:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi import Model
    from paddle_tpu.resilience import arm_scenario, disarm

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = Model(net)
    m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                      parameters=m.parameters()),
              loss=nn.CrossEntropyLoss())
    guard = m.enable_step_guard()

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 2, (16,)).astype(np.int64))

    arm_scenario(scenario)
    try:
        for _ in range(6):
            m.train_batch(x, y)
    finally:
        disarm()
    assert guard.skipped > 0, "scenario never produced a non-finite loss"
    weights = [v.numpy() for v in net.state_dict().values()]
    assert all(np.isfinite(w).all() for w in weights), \
        "NaN reached the weights — the guard failed"
    return (f"{guard.steps} steps, {guard.skipped} non-finite skipped, "
            f"weights finite")


def _drill_serve(scenario: str) -> str:
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ContinuousBatcher
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    from paddle_tpu.resilience import (Overloaded, TransientChaosError,
                                       arm_scenario, disarm)

    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    model.eval()
    b = ContinuousBatcher(model, max_batch=2, s_max=32, compile=False,
                          max_queue_depth=2)
    b.submit(np.arange(4), 4)
    b.submit(np.arange(4), 4)
    shed = 0
    try:
        b.submit(np.arange(4), 4)
    except Overloaded:
        shed = 1
    assert shed == 1, "queue at capacity did not shed"

    arm_scenario(scenario)
    faults = 0
    try:
        for _ in range(50):
            try:
                b.step()
            except TransientChaosError:
                faults += 1
            if not b._has_work():
                break
    finally:
        disarm()
    st = b.stats()
    assert st["completed_requests"] == 2, st
    assert st["requests_shed"] == 1, st
    assert b.health.ready(), f"engine not ready after drill: {b.health.state}"
    return (f"shed {st['requests_shed']}, rode out {faults} injected step "
            f"faults, completed {st['completed_requests']}, health "
            f"{b.health.state}")


def _drill_selfheal(scenario: str) -> str:
    """The closed remediation loop under the deterministic traffic
    harness: a chaos delay makes one replica a straggler, the
    AnomalyDetector/GatewayProbe pair names it, and the AutoRemediator
    drains exactly that replica (token-exact requeue) — then the
    remediation timeline is replayable with
    ``telemetry_dump --fleet $PADDLE_TELEMETRY_DIR --actions``."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.gateway import Gateway
    from paddle_tpu.inference.serving import ContinuousBatcher
    from paddle_tpu.models.gpt import GPT2Config, GPT2ForCausalLM
    from paddle_tpu.observability.anomaly import (AnomalyDetector,
                                                  GatewayProbe)
    from paddle_tpu.resilience import arm_scenario, disarm
    from paddle_tpu.resilience.remediator import (AutoRemediator,
                                                  FlapGuard, PolicyRule)
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks"))
    import traffic

    paddle.seed(0)
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    lm = GPT2ForCausalLM(cfg)
    lm.eval()

    def make(name):
        return ContinuousBatcher(lm, max_batch=8, s_max=96,
                                 compile=False)

    gw = Gateway(policy="least_loaded", max_queue_depth=128)
    gw.add_replica("r0", make("r0"))
    gw.add_replica("r1", make("r1"))
    detector = AnomalyDetector(threshold=15.0, min_samples=8)
    probe = GatewayProbe(gw, detector)
    rem = AutoRemediator(
        gw, detector=detector,
        policy=(PolicyRule("tpot_spike", "drain_replica", hysteresis=2,
                           cooldown_s=30.0),),
        replica_factory=make,
        flap_guard=FlapGuard(max_actions=4, window_s=30.0))
    # healthy per-replica baselines across every pow2 prompt rung the
    # traffic hits, BEFORE chaos arms
    rng = np.random.RandomState(7)
    for _ in range(8):
        for n in (6, 10, 20, 28):
            gw.submit(rng.randint(0, 128, (n,)), 4, tenant="warmup")
        gw.run_until_done()
        if all((t := detector._tracks.get(("tpot", r))) is not None
               and t.count >= detector.min_samples + 2
               for r in ("r0", "r1")):
            break
    gw.reset_stats()

    arm_scenario(scenario)
    try:
        spec = traffic.TrafficSpec(seed=5, steps=30, vocab=128,
                                   base_rate=0.5, prompt_lo=6,
                                   prompt_hi=16, new_lo=5, new_hi=8,
                                   shared_len=12)
        res = traffic.drive(gw, traffic.generate(spec), 0.15,
                            tick=lambda s: rem.tick())
    finally:
        disarm()
        probe.close()

    executed = rem.executed()
    assert executed, "remediator never acted on the straggler"
    assert all(a.kind == "drain_replica" and a.target == "r1"
               for a in executed), \
        f"wrong action(s): {[(a.kind, a.target) for a in executed]}"
    assert res.failed == 0 and res.completions == res.submitted, \
        "tokens lost through the drain requeue"
    rep = gw.pool.get("r1")
    assert rep.alive and not rep.routable(), \
        "straggler still routable after the drill"
    s = res.summary()
    return (f"named + drained r1 ({len(executed)} action(s)), "
            f"token-exact requeue ({res.completions}/{res.submitted} "
            f"completed, 0 failed), goodput {s['goodput_frac']:.2f}; "
            f"timeline: telemetry_dump --fleet $PADDLE_TELEMETRY_DIR "
            f"--actions")


DRILLS = {"checkpoint": _drill_checkpoint,
          "ckpt_elastic": _drill_ckpt_elastic,
          "train": _drill_train, "serve": _drill_serve,
          "selfheal": _drill_selfheal}


def _print_telemetry():
    from paddle_tpu.observability.metrics import get_registry
    reg = get_registry()
    for name in ("faults_injected_total", "retry_attempts_total",
                 "recoveries_total", "requests_shed_total",
                 "train_nonfinite_steps_total"):
        fam = reg.get(name)
        if fam is None:
            continue
        children = fam.children() if hasattr(fam, "children") else [fam]
        for c in children:
            if c.value:
                print(f"  {name}{c.labels or ''} = {c.value}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", nargs="?", choices=[*DRILLS, "all"],
                    default="all", help="which subsystem to drill")
    ap.add_argument("--scenario", default=None,
                    help="chaos scenario spec (default: the mode's "
                         "canonical drill, or $PADDLE_CHAOS if set)")
    ap.add_argument("--list", action="store_true",
                    help="print the default scenarios and exit")
    ap.add_argument("--witness", action="store_true",
                    help="arm the lock-order witness (PADDLE_LOCK_WITNESS"
                         "=1) and dump witness_<mode>.json per drill into "
                         "the telemetry dir for race_check --witness")
    args = ap.parse_args(argv)

    if args.witness:
        os.environ.setdefault("PADDLE_LOCK_WITNESS", "1")

    if args.list:
        for mode, spec in DEFAULT_SCENARIOS.items():
            print(f"{mode:12} {spec}")
        return 0

    # every drill is self-forensic: the flight recorder journals spans,
    # chaos injections, and checkpoint commits into PADDLE_TELEMETRY_DIR
    # (a temp dir unless the operator pointed it somewhere durable), and
    # each drill leaves a postmortem artifact beside its report
    tele_dir = os.environ.get("PADDLE_TELEMETRY_DIR")
    if not tele_dir:
        tele_dir = tempfile.mkdtemp(prefix="chaos_telemetry_")
        os.environ["PADDLE_TELEMETRY_DIR"] = tele_dir
    print(f"[chaos] telemetry dir: {tele_dir}")

    modes = list(DRILLS) if args.mode == "all" else [args.mode]
    failures = 0
    for mode in modes:
        scenario = (args.scenario or os.environ.get("PADDLE_CHAOS")
                    or DEFAULT_SCENARIOS[mode])
        print(f"[chaos:{mode}] scenario: {scenario}")
        _witness_reset()
        try:
            outcome = DRILLS[mode](scenario)
            print(f"[chaos:{mode}] RECOVERED — {outcome}")
        except AssertionError as exc:
            failures += 1
            print(f"[chaos:{mode}] FAILED — {exc}")
        _write_postmortem(tele_dir, mode)
        _write_witness(tele_dir, mode)
    print("-- telemetry --")
    _print_telemetry()
    return 1 if failures else 0


def _witness_reset() -> None:
    """Per-drill isolation: one drill's observed lock order must not
    leak CC405 edges into the next drill's dump."""
    from paddle_tpu.utils.locks import reset_witness, witness_enabled
    if witness_enabled():
        reset_witness()


def _write_witness(tele_dir: str, mode: str) -> None:
    from paddle_tpu.utils.locks import dump_witness, witness_enabled
    if not witness_enabled():
        return
    path = os.path.join(tele_dir, f"witness_{mode}.json")
    try:
        dump_witness(path)
    except Exception as exc:  # forensics must not flip a drill verdict
        print(f"[chaos:{mode}] witness dump unavailable: {exc}")
        return
    print(f"[chaos:{mode}] lock witness: {path} "
          f"(audit: tools/race_check.py --witness {tele_dir})")


def _write_postmortem(tele_dir: str, mode: str) -> None:
    import json

    from paddle_tpu.observability.flight import build_postmortem
    try:
        pm = build_postmortem(tele_dir)
    except Exception as exc:  # forensics must not flip a drill verdict
        print(f"[chaos:{mode}] postmortem unavailable: {exc}")
        return
    path = os.path.join(tele_dir, f"postmortem_{mode}.json")
    with open(path, "w") as f:
        json.dump(pm, f, indent=2, default=str)
    n_events = sum(v.get("events", 0) for v in pm["ranks"].values()
                   if isinstance(v, dict))
    print(f"[chaos:{mode}] postmortem: {path} "
          f"({len(pm['ranks'])} rank(s), {n_events} ring events)")


if __name__ == "__main__":
    sys.exit(main())
