#!/usr/bin/env python
"""Static SPMD shard-safety + HBM-footprint gate (SH/MEM rules).

    python tools/shard_check.py                        # gate PLAN_7B.json
    python tools/shard_check.py --json                 # machine output
    python tools/shard_check.py --mesh 7               # what-if mesh
    python tools/shard_check.py --batch 64             # what-if batch
    python tools/shard_check.py --hbm-gib 32 --strict

Evaluates every training variant of PLAN_7B.json (SH201 axis
divisibility, SH203 collectives vs the ROOFLINE.json interconnect
budget, SH204 FSDP replication waste, MEM301 per-chip HBM budget,
MEM302 remat/donation headroom) plus the gateway serving buckets
(TP weights + per-rung KV cache). Variants the plan already records as
infeasible (``fits_v5e_16gib: false``) are documented baselines, not
errors — overriding --batch/--seq/--hbm-gib re-opens the check.

Exit status: 0 when no ERROR-severity findings survive the baseline;
1 otherwise (--strict fails on warnings too). Same Finding/baseline
machinery as tpu_lint; deliberately does NOT import the paddle_tpu
package (and therefore not jax) — the rule engine (analysis/sharding.py,
analysis/memory.py, analysis/findings.py) is stdlib-only and loaded
straight off the source tree, so the tier-1 gate runs in well under a
second.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_ANALYSIS_DIR = os.path.join(_REPO, "paddle_tpu", "analysis")
sys.path.insert(0, _ANALYSIS_DIR)

import findings as findings_mod  # noqa: E402  (stdlib-only, loaded directly)
import memory as memory_mod      # noqa: E402
import sharding as sharding_mod  # noqa: E402

DEFAULT_PLAN = os.path.join(_REPO, "PLAN_7B.json")
DEFAULT_ROOFLINE = os.path.join(_REPO, "ROOFLINE.json")
DEFAULT_BASELINE = os.path.join(_HERE, "shard_check_baseline.json")


def _load_json(path):
    with open(path) as fh:
        return json.load(fh)


def _check_runtime_dump(dump, hbm_gib=None, file="<runtime>"):
    """Gate a ``MeshRuntime.describe()`` JSON dump — the EXACT specs a
    live mesh program executes (not the PLAN mirror). Re-runs SH201 over
    every param spec (train + serving shard-group) and MEM301 over the
    per-chip byte accounting, so CI lints what runs.

    Returns (findings, rows) where rows is one per-chip byte summary.
    """
    results = []
    mesh = dump.get("mesh") or {}
    mesh_spec = sharding_mod.MeshSpec.from_any(mesh)
    budget = hbm_gib if hbm_gib is not None else dump.get("hbm_per_chip_gib")

    def _frac(spec):
        deg = 1
        for d in spec:
            for a in (d if isinstance(d, (list, tuple)) else
                      (d,) if d else ()):
                deg *= mesh_spec.axes.get(a, 1)
        return 1.0 / max(deg, 1)

    per_chip = 0.0
    n_params = 0
    entries = dict(dump.get("params") or {})
    serving = dump.get("serving") or {}
    for k, v in (serving.get("params") or {}).items():
        if isinstance(v, dict):           # runtime dumps carry shapes
            entries.setdefault(f"serving:{k}", v)
    for name, ent in entries.items():
        if not isinstance(ent, dict) or "shape" not in ent:
            continue
        shape = tuple(ent["shape"])
        spec = tuple(tuple(d) if isinstance(d, list) else d
                     for d in ent.get("spec", ()))
        results.extend(sharding_mod.check_spec_divisibility(
            name, shape, spec, mesh_spec, file=file))
        per_chip += sharding_mod.nbytes(
            shape, ent.get("dtype", "float32")) * _frac(spec)
        n_params += 1
    for ent in dump.get("batch") or []:
        spec = tuple(tuple(d) if isinstance(d, list) else d
                     for d in ent.get("spec", ()))
        per_chip += sharding_mod.nbytes(
            tuple(ent["shape"]), ent.get("dtype", "float32")) * _frac(spec)

    # prefer the runtime's own liveness-walk prediction (counts masters/
    # optimizer state/transients); fall back to the raw param accounting
    memory = dump.get("memory") or {}
    peak = memory.get("predicted_peak_bytes") or per_chip
    gib = 1024.0 ** 3
    if budget is not None and peak > budget * gib:
        results.append(findings_mod.Finding(
            "MEM301",
            f"runtime mesh program needs {peak / gib:.3f} GiB/chip but "
            f"hbm_per_chip_gib is {budget:.3f} — OOM before step 1",
            file=file, severity=findings_mod.ERROR,
            extra={"peak_bytes": peak, "budget_gib": budget}))
    rows = [{"mesh": dict(mesh), "n_params": n_params,
             "param_bytes_per_chip": per_chip,
             "predicted_peak_bytes": peak,
             "hbm_per_chip_gib": budget,
             "fits": budget is None or peak <= budget * gib}]
    return results, rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shard_check",
        description="paddle_tpu SPMD shard-safety + HBM budget gate "
                    "(SH/MEM rules)")
    ap.add_argument("--plan", default=DEFAULT_PLAN,
                    help="PLAN_7B.json to gate (default: repo root)")
    ap.add_argument("--from-runtime", default=None, metavar="DUMP",
                    help="gate a MeshRuntime.describe() JSON dump (the "
                         "specs a live mesh program executes) instead of "
                         "the PLAN mirror; '-' reads stdin")
    ap.add_argument("--roofline", default=DEFAULT_ROOFLINE,
                    help="ROOFLINE.json for the SH203 interconnect budget "
                         "(pass 'none' to skip SH203)")
    ap.add_argument("--mesh", type=int, default=None,
                    help="override the mesh size (chips)")
    ap.add_argument("--batch", type=int, default=None,
                    help="override the per-variant global batch "
                         "(re-opens documented-infeasible variants)")
    ap.add_argument("--seq", type=int, default=None,
                    help="override the sequence length")
    ap.add_argument("--hbm-gib", type=float, default=None,
                    help="override hbm_per_chip_gib")
    ap.add_argument("--max-serving-batch", type=int, default=8,
                    help="concurrent sequences priced per serving bucket")
    ap.add_argument("--no-serving", action="store_true",
                    help="skip the gateway serving-bucket audit")
    ap.add_argument("--json", action="store_true",
                    help="emit findings + tables as JSON")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to restrict to")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of accepted findings "
                         "(default: tools/shard_check_baseline.json; "
                         "pass 'none' to disable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too, and error even "
                         "on documented-infeasible variants")
    args = ap.parse_args(argv)

    if args.from_runtime:
        dump = (json.load(sys.stdin) if args.from_runtime == "-"
                else _load_json(args.from_runtime))
        results, rows = _check_runtime_dump(
            dump, hbm_gib=args.hbm_gib,
            file=(os.path.basename(args.from_runtime)
                  if args.from_runtime != "-" else "<stdin>"))
        if args.rules:
            wanted = {r.strip().upper() for r in args.rules.split(",")}
            results = [f for f in results if f.rule in wanted]
        if args.json:
            print(json.dumps({
                "mode": "from-runtime",
                "runtime": rows,
                "findings": [f.to_dict() for f in results],
                "summary": findings_mod.summarize(results)}, indent=2))
        else:
            for r in rows:
                mark = "ok  " if r["fits"] else "OVER"
                gib = 1024.0 ** 3
                print(f"  [{mark}] runtime mesh {r['mesh']} "
                      f"{r['n_params']} params "
                      f"{r['predicted_peak_bytes'] / gib:.3f} GiB/chip "
                      f"(budget {r['hbm_per_chip_gib']})")
            for f in results:
                print(f)
            print(findings_mod.summarize(results))
        if findings_mod.has_errors(results):
            return 1
        return 1 if (args.strict and results) else 0

    plan = _load_json(args.plan)
    plan_name = os.path.basename(args.plan)
    roofline = None
    if args.roofline and args.roofline.lower() != "none" \
            and os.path.exists(args.roofline):
        roofline = _load_json(args.roofline)

    mesh_n = args.mesh or sharding_mod.plan_mesh_size(plan)
    results = []
    rows: list = []

    results.extend(sharding_mod.check_plan_sharding(
        plan, mesh_size=mesh_n, roofline=roofline, file=plan_name))
    results.extend(memory_mod.check_plan_memory(
        plan, hbm_gib=args.hbm_gib, batch=args.batch, seq=args.seq,
        strict=args.strict, rows=rows, file=plan_name))

    serving = {"rows": [], "findings": []}
    if not args.no_serving:
        serving = memory_mod.serving_bucket_report(
            plan, mesh_size=mesh_n, hbm_gib=args.hbm_gib,
            max_batch=args.max_serving_batch, file=plan_name)
        results.extend(serving["findings"])

    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        results = [f for f in results if f.rule in wanted]

    if args.write_baseline:
        path = (args.baseline if args.baseline.lower() != "none"
                else DEFAULT_BASELINE)
        findings_mod.write_baseline(results, path)
        print(f"wrote {len(results)} finding(s) to {path}")
        return 0

    if args.baseline.lower() != "none":
        baseline = findings_mod.load_baseline(args.baseline)
        if baseline:
            results = findings_mod.apply_baseline(results, baseline)

    if args.json:
        print(json.dumps({
            "mesh": mesh_n,
            "variants": rows,
            "serving": serving["rows"],
            "findings": [f.to_dict() for f in results],
            "summary": findings_mod.summarize(results)}, indent=2))
    else:
        print(f"mesh: {mesh_n} chips; plan: {plan_name}")
        for r in rows:
            mark = "ok  " if r["fits"] else "OVER"
            print(f"  [{mark}] train {r['variant']:<8} batch {r['batch']:>3}"
                  f" seq {r['seq']:>5} remat={str(r['remat']):<9}"
                  f" {r['live_gib']:>8.3f} GiB ({r['basis']})")
        for r in serving["rows"]:
            mark = "ok  " if r["fits"] else "OVER"
            print(f"  [{mark}] serve bucket seq {r['bucket']:>5} x"
                  f" {r['max_batch']:>2} seqs {r['live_gib']:>8.3f} GiB"
                  f" (weights {r['weights_gib']} + kv {r['kv_gib']})")
        for f in results:
            print(f)
        print(findings_mod.summarize(results))

    if findings_mod.has_errors(results):
        return 1
    if args.strict and results:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
