#!/usr/bin/env python
"""Measure achieved device peaks: bf16 matmul TFLOP/s + HBM stream GB/s.

VERDICT r4 item 3: the roofline model (tools/roofline.py) assumes v5e
datasheet peaks (197 TFLOP/s bf16, 819 GB/s HBM). This tool measures
what the chip actually delivers through our stack so the roofline's
ceiling is grounded in reality, the way the reference autotunes against
the device rather than a spec sheet
(paddle/phi/kernels/autotune/switch_autotune.cc).

Two microbenchmarks, both plain jitted XLA ops (the op class that has
been hardware-validated since round 3 — no first-contact Mosaic risk):

- matmul: square bf16 matmuls over a size sweep; achieved TFLOP/s =
  2*M*N*K / t.  The max over sizes approximates the MXU peak as seen
  from JAX (includes dispatch overhead at small sizes; large sizes
  amortize it).
- stream: out = x * 2.0 + 1.0 over a ~1 GiB bf16 array; traffic is
  read N + write N bytes.  Achieved GB/s approximates usable HBM
  bandwidth for the fused-elementwise traffic the roofline bills.

Writes MEASURED_PEAKS.json (atomic) and prints one JSON line.  Safe to
run on CPU for plumbing tests (records "tpu": false; roofline ignores
non-TPU captures).

Usage: python tools/measure_peaks.py [--iters 20] [--stream-mib 1024]
"""
from __future__ import annotations

import argparse
import json
import os
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "MEASURED_PEAKS.json")


def _time_fn(fn, *args, iters):
    """Median wall time of fn(*args) over `iters` timed calls (1 warmup)."""
    fn(*args).block_until_ready()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def measure_matmul(iters, sizes=(2048, 4096, 6144, 8192)):
    import jax
    import jax.numpy as jnp

    results = []
    for n in sizes:
        key = jax.random.PRNGKey(n)
        a = jax.random.normal(key, (n, n), jnp.bfloat16)
        b = jax.random.normal(key, (n, n), jnp.bfloat16)

        @jax.jit
        def mm(a, b):
            return a @ b

        t = _time_fn(mm, a, b, iters=iters)
        tflops = 2 * n ** 3 / t / 1e12
        results.append({"n": n, "t_ms": round(t * 1e3, 3),
                        "tflops": round(tflops, 1)})
    return results


def measure_dispatch(iters):
    """Median wall time of a trivially-small jitted op, i.e. the
    per-dispatch overhead (through the axon relay this is network
    round-trip latency). This is the cost the serving engine's
    decode_block=K amortizes: with per-token dispatch the ceiling is
    1/dispatch_latency tokens/s/slot regardless of model size."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((8, 8), jnp.float32)

    @jax.jit
    def tiny(x):
        return x + 1.0

    t = _time_fn(tiny, x, iters=max(iters, 10))
    return {"t_ms": round(t * 1e3, 3)}


def measure_stream(iters, mib):
    import jax
    import jax.numpy as jnp

    n = mib * 1024 * 1024 // 2          # bf16 elements
    x = jnp.ones((n,), jnp.bfloat16)

    @jax.jit
    def axpy(x):
        return x * jnp.bfloat16(2.0) + jnp.bfloat16(1.0)

    t = _time_fn(axpy, x, iters=iters)
    traffic = 2 * n * 2                  # read + write, bf16
    return {"mib": mib, "t_ms": round(t * 1e3, 3),
            "gbps": round(traffic / t / 1e9, 1)}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--stream-mib", type=int, default=1024)
    args = p.parse_args(argv)

    import jax
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    sizes = (2048, 4096, 6144, 8192)
    if not on_tpu:
        # keep CPU plumbing runs cheap (single-core hosts)
        args.iters = min(args.iters, 2)
        args.stream_mib = min(args.stream_mib, 64)
        sizes = (512, 1024)

    mm = measure_matmul(args.iters, sizes)
    st = measure_stream(args.iters, args.stream_mib)
    disp = measure_dispatch(args.iters)
    rec = {
        "tpu": on_tpu,
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "matmul_tflops": max(r["tflops"] for r in mm),
        "hbm_gbps": st["gbps"],
        "dispatch_ms": disp["t_ms"],
        "matmul_sweep": mm,
        "stream": st,
    }
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    os.replace(tmp, OUT)
    print(json.dumps({k: rec[k] for k in
                      ("tpu", "device", "matmul_tflops", "hbm_gbps",
                       "dispatch_ms")}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
