#!/usr/bin/env python
"""Op-level compiled-program report (opprof observatory CLI).

Two modes:

  python tools/profile_report.py              # demo: live capture
  python tools/profile_report.py --json       # same, machine-readable
  python tools/profile_report.py --artifacts  # diff OPPROF_r*.json

Demo mode compiles a tiny train step on the CPU backend with the
opprof observatory enabled, then INJECTS a recompile (a second batch
shape retraces the shape-polymorphic step) and reports what the
observatory saw: per-executable op tables, op-class cost shares, the
per-op-class roofline-gap split, and a diff between the first and the
recompiled executable that NAMES which ops appeared / disappeared /
changed cost — the same analysis a real recompile storm gets.

Artifact mode reads the committed ``OPPROF_r*.json`` rounds (bench.py
writes one per run) and diffs the newest pair — no jax import.

Gated in the lint lane next to ``trace_analyze``: rc 0 and a non-empty
diff are part of the contract (tests/test_opprof.py).
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_opprof():
    """Standalone module load: artifact mode must not import jax."""
    path = os.path.join(REPO, "paddle_tpu", "observability", "opprof.py")
    spec = importlib.util.spec_from_file_location("_opprof_standalone",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _render_profile(opprof, prof, k=8):
    lines = [f"== {prof.label}  [{prof.fingerprint}]"]
    table = prof.op_class_table()
    for cls in opprof.OP_CLASSES:
        t = table[cls]
        if not t["n_ops"]:
            continue
        lines.append(f"  {cls:>13}: share {t['cost_share']:6.3f}  "
                     f"flops {t['flops']:12.3e}  bytes {t['bytes']:10.3e}"
                     f"  ({t['n_ops']} ops)")
    cu = prof.cost_units()
    lines.append("  top ops:")
    for r in prof.top_ops(k):
        lines.append(f"    {cu[r['op']]:.3e}cu  {r['class']:>13}  "
                     f"x{r['count']:<4d} {r['op']}")
    return lines


def _render_diff(d):
    lines = ["== diff"]
    for key in ("appeared", "disappeared"):
        for op in d[key]:
            lines.append(f"  {key}: {op}")
    for c in d["changed"]:
        lines.append(f"  changed: {c['op']}  share "
                     f"{c['old_share']:.4f} -> {c['new_share']:.4f}  "
                     f"(delta {c['delta']:+.4f})")
    for lbl in d["fingerprint_changed"]:
        lines.append(f"  fingerprint changed: {lbl}")
    for lbl, g in d["recompile_growth"].items():
        lines.append(f"  recompiles: {lbl}  {g['old']} -> {g['new']}")
    if len(lines) == 1:
        lines.append("  (no drift)")
    return lines


def _render_gap(opprof, split):
    lines = ["== gap attribution (fraction of step, by phase x op class)"]
    for phase, parts in split.items():
        total = sum(parts.values())
        tops = sorted(((c, v) for c, v in parts.items() if v > 0),
                      key=lambda kv: -kv[1])[:3]
        seg = "  ".join(f"{c}={v:.4f}" for c, v in tops) or "-"
        lines.append(f"  {phase:>10} (total {total:.4f}): {seg}")
    return lines


# ---------------------------------------------------------------------------
# demo mode: live capture + injected recompile
# ---------------------------------------------------------------------------

def _demo(_unused):
    sys.path.insert(0, REPO)
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import jit, nn, optimizer
    # the jit hooks file captures into the PACKAGE module — use it
    # (the standalone copy loaded for artifact mode is a distinct
    # module object with its own registry)
    from paddle_tpu.observability import opprof

    opprof.enable()
    opprof.reset_captures()
    paddle.seed(0)

    model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 8))
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())

    def loss_fn(x, y):
        d = model(x) - y
        return (d * d).mean()

    step = jit.TrainStep(loss_fn, opt, opprof_label="demo.train_step")
    rng = np.random.RandomState(0)

    def batch(b):
        return (paddle.to_tensor(rng.rand(b, 16).astype("float32")),
                paddle.to_tensor(rng.rand(b, 8).astype("float32")))

    x, y = batch(4)
    step(x, y)   # eager discovery
    step(x, y)   # first compiled execution -> capture #1
    x2, y2 = batch(6)
    step(x2, y2)  # injected recompile (shape retrace) -> capture #2

    profs = opprof.get_captures()["demo.train_step"]
    d = opprof.diff({"captures": {"demo.train_step": profs[0].to_dict()},
                     "recompiles": {"demo.train_step": 1}},
                    {"captures": {"demo.train_step": profs[-1].to_dict()},
                     "recompiles": {"demo.train_step": len(profs)}},
                    share_tol=0.0)
    attr = {"compute_frac": 0.30, "memory_frac": 0.25,
            "overhead_frac": 0.45}  # CPU proxy: a representative split
    split = opprof.publish_gap_attribution(attr, profile=profs[-1])
    return {
        "mode": "demo",
        "profiles": {p.fingerprint: p.to_dict() for p in profs},
        "recompiles": opprof.recompile_counts(),
        "top_op_classes": opprof.top_op_classes(profs[-1]),
        "gap_attribution": split,
        "diff": d,
    }, profs, d, split


# ---------------------------------------------------------------------------
# artifact mode
# ---------------------------------------------------------------------------

def _artifacts(opprof, paths):
    paths = paths or opprof.artifact_paths(REPO)
    docs = [(p, opprof.load_artifact(p)) for p in paths]
    docs = [(p, d) for p, d in docs if d is not None]
    if not docs:
        return {"mode": "artifacts", "error": "no OPPROF_r*.json found"}
    newest_path, newest = docs[-1]
    out = {
        "mode": "artifacts",
        "artifact": os.path.basename(newest_path),
        "headline": newest.get("headline"),
        "recompiles": newest.get("recompiles"),
        "gap_attribution": newest.get("gap_attribution"),
        "labels": sorted((newest.get("captures") or {}).keys()),
    }
    if len(docs) >= 2:
        prev_path, prev = docs[-2]
        out["vs"] = os.path.basename(prev_path)
        out["diff"] = opprof.diff(prev, newest)
    return out


def main(argv):
    opprof = _load_opprof()
    as_json = "--json" in argv
    args = [a for a in argv[1:] if not a.startswith("--")]
    if "--artifacts" in argv or args:
        out = _artifacts(opprof, args or None)
        if as_json:
            print(json.dumps(out, indent=1))
            return 0 if "error" not in out else 1
        if "error" in out:
            print(out["error"])
            return 1
        print(f"== {out['artifact']}  "
              f"(labels: {', '.join(out['labels'])})")
        h = out.get("headline") or {}
        print(f"  headline: top class {h.get('top_class')} "
              f"share {h.get('top_share')}  "
              f"recompiles {h.get('n_recompiles')}")
        if out.get("gap_attribution"):
            for line in _render_gap(opprof, out["gap_attribution"]):
                print(line)
        if "diff" in out:
            print(f"-- vs {out['vs']}")
            for line in _render_diff(out["diff"]):
                print(line)
        return 0
    out, profs, d, split = _demo(opprof)
    if as_json:
        print(json.dumps(out, indent=1))
        return 0
    for p in profs:
        for line in _render_profile(opprof, p):
            print(line)
    for line in _render_gap(opprof, split):
        print(line)
    for line in _render_diff(d):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
