#!/usr/bin/env python
"""Roofline model for the bench.py training configs (VERDICT r3 #1).

Computes, from first principles (no hardware needed), where a training
step's time must go on a v5e chip: MXU FLOPs, HBM traffic per step
(weights fwd/bwd, optimizer-state update, saved activations, logits),
the resulting compute/memory time bounds, and the measured-MFU ceiling
those bounds imply. Next healthy window, compare `BENCH_TPU_SNAPSHOT`
against `ROOFLINE.json`: measured step time ~ compute bound -> MXU-bound
and healthy; >> bound -> the gap names the suspect (opt traffic,
attention workspace, remat replay).

Peak numbers: v5e ~197 TFLOP/s bf16, ~819 GB/s HBM (public chip specs)
by default.  If tools/measure_peaks.py has captured MEASURED_PEAKS.json
on real hardware (VERDICT r4 item 3), the measured peaks are used
instead and the output carries `"measured": true` plus a
modeled-vs-measured comparison block, so the ceiling reflects what the
chip delivers through our stack rather than the datasheet.

Usage: python tools/roofline.py   # prints table + writes ROOFLINE.json
"""
from __future__ import annotations

import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "ROOFLINE.json")
PEAKS = os.path.join(REPO, "MEASURED_PEAKS.json")

DATASHEET_FLOPS = 197e12      # v5e bf16
DATASHEET_HBM = 819e9         # v5e bytes/s

PEAK_FLOPS = DATASHEET_FLOPS
PEAK_HBM = DATASHEET_HBM
MEASURED = None
if os.path.exists(PEAKS):
    try:
        _p = json.load(open(PEAKS))
        # read every required key BEFORE claiming measured peaks: a
        # malformed/partial capture must leave the datasheet numbers AND
        # measured:false, never a half-applied mix
        if _p.get("tpu"):
            _flops = float(_p["matmul_tflops"]) * 1e12
            _hbm = float(_p["hbm_gbps"]) * 1e9
            MEASURED = _p
            PEAK_FLOPS, PEAK_HBM = _flops, _hbm
    except (ValueError, KeyError, TypeError):
        pass


def llama_params(V, H, I, L, heads, kv_heads):
    head_dim = H // heads
    attn = H * (heads * head_dim) + 2 * H * (kv_heads * head_dim) \
        + (heads * head_dim) * H
    mlp = 3 * H * I
    return V * H * 2 + L * (attn + mlp + 2 * H) + H


def analyze(name, V, H, I, L, heads, kv_heads, batch, seq, remat):
    P = llama_params(V, H, I, L, heads, kv_heads)
    tokens = batch * seq
    att_flops_tok = 12 * L * H * seq          # bench.py's MFU formula term
    flops_counted = (6 * P + att_flops_tok) * tokens
    # real executed FLOPs: selective remat replays elementwise (~free) but
    # the flash custom-vjp recomputes the attention forward in the
    # backward (+4*L*H*seq per token); full remat replays the whole
    # forward (+2P per token)
    replay = {"selective": 4 * L * H * seq, "full": 2 * P + 4 * L * H * seq,
              "off": 0}[remat] * tokens
    flops_real = flops_counted + replay

    wbytes = 2 * P                             # bf16 weights
    # HBM traffic per step (bytes):
    traffic = {
        # fwd reads weights once; bwd reads them for dgrad + wgrad
        "weights_fwd_bwd": 3 * wbytes,
        # AdamW multi-precision: read master+m+v+grad(f32), write
        # master+m+v(f32) + bf16 params
        "optimizer_update": (4 + 3) * 4 * P + 2 * P,
        # saved activations (selective: the no-batch-dim dot outputs),
        # written in fwd + read in bwd
        "saved_activations": 2 * _saved_bytes(H, I, L, tokens, remat),
        # logits fp32 + softmax grad traffic (write + read + grad)
        "logits": 3 * tokens * V * 4,
    }
    total_bytes = sum(traffic.values())

    t_compute = flops_real / PEAK_FLOPS
    t_memory = total_bytes / PEAK_HBM
    # perfectly-overlapped lower bound on step time
    t_step = max(t_compute, t_memory)
    tok_per_s = tokens / t_step
    # bench.py counts flops_counted: the measured-MFU ceiling
    mfu_ceiling = flops_counted / (t_step * PEAK_FLOPS)
    return {
        "config": name, "params": P, "batch": batch, "seq": seq,
        "remat": remat,
        "flops_counted": flops_counted, "flops_real": flops_real,
        "hbm_bytes": traffic | {"total": total_bytes},
        "t_compute_ms": round(t_compute * 1e3, 2),
        "t_memory_ms": round(t_memory * 1e3, 2),
        "bound": "compute" if t_compute >= t_memory else "memory",
        "tokens_per_s_bound": round(tok_per_s, 0),
        "measured_mfu_ceiling": round(mfu_ceiling, 3),
    }


def _saved_bytes(H, I, L, tokens, remat):
    if remat == "full":
        return tokens * H * 2 * L              # layer inputs only
    # selective: qkv (3H) + o (H) + gate/up (2I) + down (H) per layer, bf16
    per_tok_layer = (3 * H + H + 2 * I + H) * 2
    return tokens * per_tok_layer * L


BENCH_CONFIGS = [
    # mirrors bench.py main(): (V, H, I, L, heads, kvh, batch, seq, remat)
    ("large", 32000, 1536, 4096, 16, 12, 12, 4, 2048, "selective"),
    ("medium", 32000, 1152, 3072, 16, 9, 9, 4, 2048, "selective"),
    ("small", 32000, 1024, 2816, 24, 16, 16, 4, 1024, "off"),
]


def main():
    rows = [analyze(*cfg) for cfg in BENCH_CONFIGS]
    for r in rows:
        print(f"{r['config']:7s} P={r['params']/1e6:6.0f}M "
              f"{r['bound']}-bound  t_mxu={r['t_compute_ms']:7.2f}ms "
              f"t_hbm={r['t_memory_ms']:6.2f}ms  "
              f"<= {r['tokens_per_s_bound']:8.0f} tok/s  "
              f"MFU ceiling {r['measured_mfu_ceiling']}")
    out = {"peak_flops": PEAK_FLOPS, "peak_hbm": PEAK_HBM,
           "measured": MEASURED is not None, "configs": rows}
    if MEASURED is not None:
        out["peaks_source"] = {
            "captured_at": MEASURED.get("captured_at"),
            "device": MEASURED.get("device"),
            "modeled_vs_measured": {
                "flops": [DATASHEET_FLOPS, PEAK_FLOPS],
                "hbm": [DATASHEET_HBM, PEAK_HBM],
            },
        }
        print(f"peaks: MEASURED {PEAK_FLOPS/1e12:.0f} TFLOP/s "
              f"{PEAK_HBM/1e9:.0f} GB/s (datasheet "
              f"{DATASHEET_FLOPS/1e12:.0f}/{DATASHEET_HBM/1e9:.0f})")
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    os.replace(tmp, OUT)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
