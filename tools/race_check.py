#!/usr/bin/env python
"""Concurrency-safety linter CLI (CC* rules of paddle_tpu.analysis).

Static half — the whole-repo lock-acquisition graph:

    python tools/race_check.py paddle_tpu tools benchmarks   # text report
    python tools/race_check.py --json paddle_tpu             # machine output
    python tools/race_check.py --write-baseline paddle_tpu tools benchmarks
    python tools/race_check.py --rules CC401,CC402 paddle_tpu

Dynamic half — audit lock-witness dumps recorded by a run with
``PADDLE_LOCK_WITNESS=1`` (see ``paddle_tpu/utils/locks.py`` and the
``tools/chaos_run.py`` witness leg):

    python tools/race_check.py --witness /tmp/chaos_out       # dir scan
    python tools/race_check.py --witness witness_kill.json    # one dump

Exit status: 0 when no ERROR-severity findings survive suppressions and
the baseline; 1 otherwise (CC401 lock-order cycles and CC405 witnessed
inversions are errors; CC402/403/404/406 are warnings — use --strict to
fail on those too).

Deliberately does NOT import the paddle_tpu package (and therefore not
jax): the rule engine (analysis/concurrency.py, analysis/findings.py)
is stdlib-only and loaded straight off the source tree, so the tier-1
lint gate runs in a couple of seconds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_ANALYSIS_DIR = os.path.join(_REPO, "paddle_tpu", "analysis")
sys.path.insert(0, _ANALYSIS_DIR)

import concurrency   # noqa: E402  (stdlib-only modules, loaded directly)
import findings as findings_mod  # noqa: E402

DEFAULT_BASELINE = os.path.join(_HERE, "race_check_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="race_check",
        description="paddle_tpu concurrency-safety linter (CC rules)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of accepted findings "
                         "(default: tools/race_check_baseline.json; "
                         "pass 'none' to disable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to restrict to")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--witness", action="append", default=[],
                    metavar="PATH",
                    help="audit a lock-witness dump (witness_*.json) or "
                         "a directory of them for CC405/CC406 "
                         "(repeatable; combines with static paths)")
    args = ap.parse_args(argv)

    if not args.paths and not args.witness:
        ap.error("no paths given (and no --witness)")

    paths = [p if os.path.isabs(p) else os.path.join(os.getcwd(), p)
             for p in args.paths]
    results = concurrency.analyze_paths(paths, root=os.getcwd())

    if args.witness:
        results.extend(concurrency.audit_witness_paths(args.witness))

    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        results = [f for f in results if f.rule in wanted]

    if args.write_baseline:
        path = (args.baseline if args.baseline.lower() != "none"
                else DEFAULT_BASELINE)
        findings_mod.write_baseline(results, path)
        print(f"wrote {len(results)} finding(s) to {path}")
        return 0

    if args.baseline.lower() != "none":
        baseline = findings_mod.load_baseline(args.baseline)
        if baseline:
            results = findings_mod.apply_baseline(results, baseline)

    if args.json:
        print(json.dumps({"findings": [f.to_dict() for f in results],
                          "summary": findings_mod.summarize(results)},
                         indent=2))
    else:
        for f in results:
            print(f)
        print(findings_mod.summarize(results))

    if findings_mod.has_errors(results):
        return 1
    if args.strict and results:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
