#!/usr/bin/env python
"""Llama-2-7B flagship memory plan for a v5e-16 pod (VERDICT r3 #4).

AOT-compiles the FULL sharded train step (forward + backward + AdamW with
fp32 master weights, bf16 compute) for a 16-device mesh and reports XLA's
per-chip memory estimate from buffer assignment — no parameter buffer is
ever materialized (a 7B model cannot exist on a 16-virtual-device host:
replicated bf16 weights alone would need 216 GB).

The step is a PURE function: the parameter/optimizer pytree is an
argument (ShapeDtypeStruct at compile time), mirroring the shapes, dtypes
and math of paddle_tpu/models/llama.py (RMSNorm -> GQA-capable attention
-> SwiGLU, scan over stacked [L, ...] weights, jax.checkpoint remat) and
the sharding plan of shard_llama/shard_optimizer:

  - s2  (fleet sharding stage-2 analog, BASELINE.md config 3): parameters
    REPLICATED, optimizer states + master weights sharded over the 16
    chips. The reference runs this on 80 GB H100s; the plan quantifies
    why a 16 GB v5e cannot hold replicated 7B bf16 weights (13.5 GB)
    plus gradients and activations.
  - s3  (ZeRO-3 / FSDP analog, shard_llama fsdp_axis): parameters,
    masters and optimizer states all sharded; selective remat
    (dots_with_no_batch_dims_saveable, the bench.py policy).
  - s3_full: same with full per-layer remat (minimum activation memory).

Caveats (stated in the report): the CPU backend compiles XLA attention
(Mosaic/Pallas flash cannot target CPU), so the S^2 attention workspace in
`temp` is an overestimate versus the TPU path where flash streams it; and
buffer sizes come from XLA:CPU buffer assignment at identical
shapes/shardings, not a TPU HLO schedule.

Usage:  python tools/plan_7b.py            # self-execs on a 16-CPU mesh
        python tools/plan_7b.py --execute  # scaled-down real step (8 mesh)
Writes PLAN_7B.json at the repo root.

Reference parity targets: BASELINE.md config 3;
fleet/meta_parallel/sharding/group_sharded_stage2.py:46 (reference stage-2),
group_sharded_stage3.py:85 (stage-3 prefetch/offload analog).
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PLAN_7B.json")

GIB = 1024 ** 3
V5E_HBM_GIB = 16.0


def _llama7b_dims():
    """Mirror of paddle_tpu.models.llama.llama2_7b_config (32L/4096H/32
    heads, MHA, vocab 32000, SwiGLU 11008)."""
    return dict(L=32, H=4096, I=11008, V=32000, heads=32, kv_heads=32)


def _tiny_dims():
    return dict(L=4, H=256, I=688, V=2000, heads=8, kv_heads=8)


def _param_shapes(d):
    L, H, I, V = d["L"], d["H"], d["I"], d["V"]
    return {
        "embed": (V, H),
        "wq": (L, H, H), "wk": (L, H, H), "wv": (L, H, H), "wo": (L, H, H),
        "w_gate": (L, H, I), "w_up": (L, H, I), "w_down": (L, I, H),
        "ln1": (L, H), "ln2": (L, H), "ln_f": (H,),
        "lm_head": (H, V),
    }


def _build_step(d, batch, seq, remat, variant="s3", mesh=None):
    """Pure train step: (state, ids, labels) -> (state, loss).

    state = {params(bf16), master(f32), m(f32), v(f32), step(i32)}; math
    mirrors models/llama.py (cited there against the reference's fused
    kernels) and optimizer.AdamW with multi_precision=True.

    variant "s3" (ZeRO-3/FSDP): the bf16 compute params are DERIVED from
    the sharded fp32 master inside the step (state["params"] exists for
    checkpoint parity but the step never reads it, so XLA prunes it);
    per-layer weight gathers appear as temps.
    variant "s2" (stage-2): bf16 params are live REPLICATED state; grads
    are constrained to the sharded layout (GSPMD lowers the data-parallel
    reduction to a reduce-scatter, the reference's stage-2 grad sharding),
    the sharded fp32 master updates, and the new replicated params are
    all-gathered back — so the 13.5 GB replicated weight residency is
    honestly part of the per-chip estimate.
    """
    import jax
    import jax.numpy as jnp

    heads, kv_heads = d["heads"], d["kv_heads"]
    head_dim = d["H"] // heads
    scale = head_dim ** -0.5

    def rms(x, w, eps=1e-5):
        r = jax.lax.rsqrt(jnp.mean(
            x.astype(jnp.float32) ** 2, -1, keepdims=True) + eps)
        return (x * r.astype(x.dtype)) * w

    def rope(x, pos):
        # [B, S, h, dh] -> rotate pairs; mirrors llama.py _rope_cos_sin
        half = head_dim // 2
        inv = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
        ang = pos[:, None].astype(jnp.float32) * inv[None]
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        x1, x2 = x[..., :half], x[..., half:]
        cos = cos[None, :, None, :].astype(x.dtype)
        sin = sin[None, :, None, :].astype(x.dtype)
        return jnp.concatenate([x1 * cos - x2 * sin,
                                x1 * sin + x2 * cos], -1)

    def _anchor(h):
        # activation anchor (mirrors shard_llama's batch_axes install):
        # batch stays sharded over the mesh, hidden replicated — without
        # it GSPMD may all-gather the batch to resolve the batch-sharded x
        # vs in-dim-sharded w conflict, 16x-ing every saved residual
        if mesh is None:
            return h
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.lax.with_sharding_constraint(
            h, NamedSharding(mesh, P("z", *([None] * (h.ndim - 1)))))

    def layer(h, w):
        h = _anchor(h)
        B, S, H = h.shape
        pos = jnp.arange(S)
        x = rms(h, w["ln1"])
        q = (x @ w["wq"]).reshape(B, S, heads, head_dim)
        k = (x @ w["wk"]).reshape(B, S, kv_heads, head_dim)
        v = (x @ w["wv"]).reshape(B, S, kv_heads, head_dim)
        q, k = rope(q, pos), rope(k, pos)
        if kv_heads != heads:
            k = jnp.repeat(k, heads // kv_heads, 2)
            v = jnp.repeat(v, heads // kv_heads, 2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        causal = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(causal, s, jnp.asarray(-1e30, s.dtype))
        p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(h.dtype)
        att = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, S, H)
        h = h + att @ w["wo"]
        x = rms(h, w["ln2"])
        mlp = (jax.nn.silu(x @ w["w_gate"]) * (x @ w["w_up"])) @ w["w_down"]
        return _anchor(h + mlp)

    if remat == "selective":
        layer = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif remat == "full":
        layer = jax.checkpoint(layer)

    def forward(params, ids, labels):
        h = params["embed"][ids]
        stacked = {k: params[k] for k in
                   ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                    "ln1", "ln2")}

        def body(h, w):
            return layer(h, w), None

        h, _ = jax.lax.scan(body, h, stacked)
        h = rms(h, params["ln_f"])
        logits = (h @ params["lm_head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, -1)
        nll = -jnp.take_along_axis(logp, labels[..., None], -1)
        return nll.mean()

    def _adamw(state, grads_f32):
        t = state["step"] + 1
        b1, b2, lr, eps, wd = 0.9, 0.999, 1e-4, 1e-8, 0.01
        m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                         state["m"], grads_f32)
        v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g,
                         state["v"], grads_f32)
        c1 = 1 - b1 ** t.astype(jnp.float32)
        c2 = 1 - b2 ** t.astype(jnp.float32)
        master = jax.tree.map(
            lambda p, m_, v_: p - lr * ((m_ / c1) / (jnp.sqrt(v_ / c2)
                                                     + eps) + wd * p),
            state["master"], m, v)
        return master, m, v, t

    def step_s3(state, ids, labels):
        def loss_of_master(master):
            params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), master)
            return forward(params, ids, labels)

        loss, grads = jax.value_and_grad(loss_of_master)(state["master"])
        master, m, v, t = _adamw(state, grads)
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), master)
        return {"params": params, "master": master, "m": m, "v": v,
                "step": t}, loss

    def step_s2(state, ids, labels):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.lax import with_sharding_constraint as wsc

        sharded, replicated = _s2_grad_shardings(d, mesh)
        loss, grads = jax.value_and_grad(
            lambda p: forward(p, ids, labels))(state["params"])
        # stage-2: grads live SHARDED (GSPMD lowers the DP reduction to a
        # reduce-scatter instead of an all-reduce)
        grads = jax.tree.map(lambda g, s: wsc(g.astype(jnp.float32), s),
                             grads, sharded)
        master, m, v, t = _adamw(state, grads)
        # updated params all-gather back to the replicated layout
        params = jax.tree.map(
            lambda x, r: wsc(x.astype(jnp.bfloat16), r), master, replicated)
        return {"params": params, "master": master, "m": m, "v": v,
                "step": t}, loss

    return step_s2 if variant == "s2" else step_s3


def _s2_grad_shardings(d, mesh):
    """(sharded, replicated) NamedSharding trees over the param shapes."""
    from jax.sharding import NamedSharding
    sharded_tree, _ = _shardings(d, mesh, "s3")
    sharded = sharded_tree["master"]
    rep_tree, _ = _shardings(d, mesh, "s2")
    replicated = rep_tree["params"]
    return sharded, replicated


def _shardings(d, mesh, variant):
    """NamedShardings mirroring shard_llama(fsdp_axis='z') /
    shard_optimizer: s3 shards every >=2D weight on a non-layer dim; s2
    replicates params but shards master/m/v (stage-2: optimizer-state +
    grad sharding, parameters replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def shard_spec(name, shape):
        if name in ("ln1", "ln2", "ln_f"):
            return P()  # per-layer norm scales: tiny, replicate
        if len(shape) == 2:  # embed [V,H] / lm_head [H,V]: shard dim 0
            return P("z", None)
        return P(None, "z", None)  # stacked [L, in, out]: shard `in`

    def of(spec):
        return NamedSharding(mesh, spec)

    shapes = _param_shapes(d)
    sharded = {k: of(shard_spec(k, s)) for k, s in shapes.items()}
    replicated = {k: of(P()) for k in shapes}
    opt_tree = sharded  # master/m/v always sharded (both variants)
    params_tree = replicated if variant == "s2" else sharded
    state_shardings = {"params": params_tree, "master": opt_tree,
                       "m": dict(opt_tree), "v": dict(opt_tree),
                       "step": of(P())}
    data_sharding = of(P("z", None))  # batch over the mesh
    return state_shardings, data_sharding


def _abstract_state(d):
    import jax
    import jax.numpy as jnp
    shapes = _param_shapes(d)

    def tree(dtype):
        return {k: jax.ShapeDtypeStruct(s, dtype) for k, s in shapes.items()}

    return {"params": tree(jnp.bfloat16), "master": tree(jnp.float32),
            "m": tree(jnp.float32), "v": tree(jnp.float32),
            "step": jax.ShapeDtypeStruct((), jnp.int32)}


def _compile_variant(d, mesh, variant, remat, batch, seq):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    step = _build_step(d, batch, seq, remat, variant=variant, mesh=mesh)
    state_sh, data_sh = _shardings(d, mesh, variant)
    state = _abstract_state(d)

    def with_sh(tree, sh):
        return jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            tree, sh)

    state = {k: (with_sh(state[k], state_sh[k])
                 if isinstance(state[k], dict)
                 else jax.ShapeDtypeStruct(state[k].shape, state[k].dtype,
                                           sharding=state_sh[k]))
             for k in state}
    ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=data_sh)
    labels = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=data_sh)

    jitted = jax.jit(step, donate_argnums=(0,))
    compiled = jitted.lower(state, ids, labels).compile()
    ma = compiled.memory_analysis()
    n_params = sum(
        functools.reduce(lambda a, b: a * b, s, 1)
        for s in _param_shapes(d).values())
    rec = {
        "variant": variant, "remat": remat, "batch": batch, "seq": seq,
        "n_params": n_params,
        "per_chip_bytes": {
            "arguments": ma.argument_size_in_bytes,
            "outputs": ma.output_size_in_bytes,
            "aliased": ma.alias_size_in_bytes,
            "temp": ma.temp_size_in_bytes,
        },
    }
    # resident = donated-in state (arguments) + workspace; donated outputs
    # alias the inputs so they are not double-counted
    live = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
            + max(0, ma.output_size_in_bytes - ma.alias_size_in_bytes))
    rec["per_chip_live_gib"] = round(live / GIB, 3)
    rec["fits_v5e_16gib"] = bool(live / GIB <= V5E_HBM_GIB)
    return rec


VARIANTS = {"s2": ("s2", "selective"), "s3": ("s3", "selective"),
            "s3_full": ("s3", "full")}


def run_plan(n_devices=16, batch=16, seq=2048, execute=False,
             variants=None):
    import numpy as np
    import jax

    devs = jax.devices()
    assert len(devs) >= n_devices, (len(devs), n_devices)
    from jax.sharding import Mesh
    mesh = Mesh(np.array(devs[:n_devices]), ("z",))

    d = _llama7b_dims()
    report = {"topology": f"{n_devices}-chip mesh (v5e-16 analog)",
              "hbm_per_chip_gib": V5E_HBM_GIB,
              "model": "llama2-7b (32L/4096H/32 heads, MHA, vocab 32000)",
              "backend": jax.devices()[0].platform,
              "note": ("compile-only buffer-assignment estimate on the CPU "
                       "backend at identical shapes/shardings; XLA "
                       "attention (no Mosaic flash on CPU) makes `temp` an "
                       "overestimate of the TPU flash path"),
              "variants": []}
    # a partial (--variants) run must not erase other variants' evidence
    try:
        with open(OUT) as f:
            prev = json.load(f)
        report["variants"] = prev.get("variants", [])
        # evidence blocks owned by sibling tools must survive a re-plan
        # (tools/slice_7b.py writes slice_7b; erasing it would let this
        # tool's own test delete the measured per-layer record)
        for carry in ("scaled_execute", "slice_7b"):
            if carry in prev:
                report[carry] = prev[carry]
    except (OSError, json.JSONDecodeError):
        pass
    wanted = variants or list(VARIANTS)
    with mesh:
        for name in wanted:
            variant, remat = VARIANTS[name]
            print(f"[plan7b] compiling {name} ...", flush=True)
            rec = _compile_variant(d, mesh, variant, remat, batch, seq)
            rec["name"] = name
            report["variants"] = [v for v in report["variants"]
                                  if v["name"] != name] + [rec]
            print(f"[plan7b] {name}: live/chip = "
                  f"{rec['per_chip_live_gib']} GiB "
                  f"(fits 16G: {rec['fits_v5e_16gib']})", flush=True)
            _write(report)  # persist incrementally: a later failure must
            # not lose the compile evidence

    if execute:
        # scaled-down, SAME structure/shardings/remat: prove the compiled
        # step actually runs and produces a finite loss on an 8-chip mesh
        td = _tiny_dims()
        n = min(8, len(devs))
        tmesh = Mesh(np.array(devs[:n]), ("z",))
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        with tmesh:
            step = _build_step(td, n, 128, "selective", mesh=tmesh)
            state_sh, data_sh = _shardings(td, tmesh, "s3")
            shapes = _param_shapes(td)

            def init(dtype):
                return {k: jnp.asarray(rng.randn(*s) * 0.02, dtype)
                        for k, s in shapes.items()}

            master = init(jnp.float32)
            state = {"params": jax.tree.map(
                         lambda x: x.astype(jnp.bfloat16), master),
                     "master": master,
                     "m": jax.tree.map(jnp.zeros_like, master),
                     "v": jax.tree.map(jnp.zeros_like, master),
                     "step": jnp.asarray(0, jnp.int32)}
            state = {
                k: (jax.tree.map(jax.device_put, state[k], state_sh[k])
                    if isinstance(state[k], dict)
                    else jax.device_put(state[k], state_sh[k]))
                for k in state}
            ids = jax.device_put(
                jnp.asarray(rng.randint(0, td["V"], (n, 128))), data_sh)
            labels = jax.device_put(
                jnp.asarray(rng.randint(0, td["V"], (n, 128))), data_sh)
            jstep = jax.jit(step, donate_argnums=(0,))
            state, loss0 = jstep(state, ids, labels)
            state, loss1 = jstep(state, ids, labels)
            report["scaled_execute"] = {
                "dims": td, "mesh": n, "loss0": float(loss0),
                "loss1": float(loss1),
                "ok": bool(np.isfinite(float(loss0))
                           and np.isfinite(float(loss1))
                           and float(loss1) < float(loss0)),
            }
            print(f"[plan7b] scaled execute: loss {float(loss0):.4f} -> "
                  f"{float(loss1):.4f}", flush=True)

    _write(report)
    return report


def _write(report):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    os.replace(tmp, OUT)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--execute", action="store_true")
    ap.add_argument("--inproc", action="store_true")
    ap.add_argument("--devices", type=int, default=16)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--variants", help="comma-separated subset of "
                    f"{sorted(VARIANTS)} (default: all)")
    args = ap.parse_args()
    if args.variants:
        unknown = [v for v in args.variants.split(",")
                   if v not in VARIANTS]
        if unknown:
            ap.error(f"unknown variant(s) {unknown}")

    if not args.inproc:
        # self-exec on a sanitized virtual-CPU mesh (wedge-immune, same
        # recipe as __graft_entry__.dryrun_multichip)
        import subprocess
        sys.path.insert(0, REPO)
        import __graft_entry__ as graft
        env = dict(os.environ)
        graft.force_cpu_env(env, args.devices)
        graft.strip_axon_pythonpath(env)
        cmd = [sys.executable, os.path.abspath(__file__), "--inproc",
               "--devices", str(args.devices), "--batch", str(args.batch),
               "--seq", str(args.seq)]
        if args.variants:
            cmd += ["--variants", args.variants]
        if args.execute:
            cmd.append("--execute")
        return subprocess.run(cmd, env=env, cwd=REPO, timeout=1800).returncode

    report = run_plan(args.devices, args.batch, args.seq, args.execute,
                      args.variants.split(",") if args.variants else None)
    fitting = [v["name"] for v in report["variants"] if v["fits_v5e_16gib"]]
    print(json.dumps({"fitting_variants": fitting}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
