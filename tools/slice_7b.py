#!/usr/bin/env python
"""Execute a true-7B-dimension slice and extrapolate to 32 layers.

VERDICT r4 item 2: PLAN_7B.json proved the s3_full variant *compiles*
and fits 16 GiB/chip, but no 7B-shaped layer had ever executed a real
step.  This tool closes that gap two ways, both recorded into
PLAN_7B.json under "slice_7b":

1. EXECUTE: an L=1 and an L=2 slice with the real Llama-2-7B layer
   dimensions (hidden 4096, 32 heads x head_dim 128, SwiGLU 11008,
   vocab 32000) runs the full sharded s3_full train step (ZeRO-3
   sharding, full remat, bf16 compute / fp32 master AdamW) on the
   8-virtual-CPU mesh.  Per-layer step time = t(L=2) - t(L=1), with
   the embed/logits residue t(L=1) - t_layer reported separately, and
   a 32-layer extrapolation t_embed + 32*t_layer.  These are
   CPU-backend timings — useful as execution evidence and for the
   linearity-in-L structure of the cost, NOT as TPU predictions (the
   roofline model owns that; see ROOFLINE.json).
2. MEMORY: AOT-compiles the same L=1/L=2 slices at the TRUE flagship
   batch 16 x seq 2048 on the 16-device mesh and fits per-chip live
   bytes linear in L; the 32-layer extrapolation is compared against
   the recorded full-32L compile (PLAN_7B.json variants[s3_full]).
   A small residual validates that XLA's buffer assignment scales the
   way the plan assumes.

Token budget: the executed slice uses batch 8 (one row per device) and
a reduced seq so a single-core host finishes in minutes; the layer
SHAPES are exactly the 7B layer's, which is what the evidence is for.

Usage:  python tools/slice_7b.py            # self-execs on CPU mesh
        python tools/slice_7b.py --inproc --seq 512
Reference parity: BASELINE.md config 3,
fleet/meta_parallel/sharding/group_sharded_stage3.py:85.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
OUT = os.path.join(REPO, "PLAN_7B.json")
GIB = 1024 ** 3


def _slice_dims(L):
    import plan_7b
    d = dict(plan_7b._llama7b_dims())
    d["L"] = L
    return d


def _measure_execute(n_mesh, seq, steps):
    """Run L=1 and L=2 true-dim slices; return timing records."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    import plan_7b

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:n_mesh]), ("z",))
    batch = n_mesh
    recs = {}
    for L in (1, 2):
        d = _slice_dims(L)
        rng = np.random.RandomState(L)
        with mesh:
            step = plan_7b._build_step(d, batch, seq, "full", mesh=mesh)
            state_sh, data_sh = plan_7b._shardings(d, mesh, "s3")
            shapes = plan_7b._param_shapes(d)
            master = {k: jnp.asarray(
                rng.standard_normal(s).astype(np.float32) * 0.02)
                for k, s in shapes.items()}
            state = {"params": jax.tree.map(
                         lambda x: x.astype(jnp.bfloat16), master),
                     "master": master,
                     "m": jax.tree.map(jnp.zeros_like, master),
                     "v": jax.tree.map(jnp.zeros_like, master),
                     "step": jnp.asarray(0, jnp.int32)}
            state = {
                k: (jax.tree.map(jax.device_put, state[k], state_sh[k])
                    if isinstance(state[k], dict)
                    else jax.device_put(state[k], state_sh[k]))
                for k in state}
            ids = jax.device_put(
                jnp.asarray(rng.randint(0, d["V"], (batch, seq))), data_sh)
            labels = jax.device_put(
                jnp.asarray(rng.randint(0, d["V"], (batch, seq))), data_sh)
            jstep = jax.jit(step, donate_argnums=(0,))
            t0 = time.perf_counter()
            state, loss0 = jstep(state, ids, labels)
            loss0 = float(loss0)
            t_compile = time.perf_counter() - t0
            times = []
            loss_last = loss0
            for _ in range(steps):
                t0 = time.perf_counter()
                state, loss = jstep(state, ids, labels)
                loss_last = float(loss)   # forces completion
                times.append(time.perf_counter() - t0)
            recs[L] = {
                "L": L, "batch": batch, "seq": seq,
                "t_step_s": round(min(times), 3),
                "t_compile_s": round(t_compile, 1),
                "loss0": round(loss0, 4), "loss_last": round(loss_last, 4),
                "ok": bool(np.isfinite(loss0) and np.isfinite(loss_last)
                           and loss_last < loss0),
            }
            print(f"[slice7b] L={L}: step {recs[L]['t_step_s']}s "
                  f"loss {loss0:.4f}->{loss_last:.4f}", flush=True)
            del state
    return recs


def _measure_memory(n_devices, batch, seq, ls=(2, 4, 8)):
    """AOT-compile L-layer slices at the flagship config; per-chip live.

    L=1 is deliberately excluded: XLA buffer assignment at trivial scan
    depth is non-monotone (an L=1 scan schedules differently enough that
    its live total can EXCEED L=2's — observed 5.14 vs 4.84 GiB), so the
    linear-in-L fit uses L >= 2 where the per-layer slope is stable."""
    import numpy as np
    import jax
    from jax.sharding import Mesh
    import plan_7b

    devs = jax.devices()
    assert len(devs) >= n_devices, (len(devs), n_devices)
    mesh = Mesh(np.array(devs[:n_devices]), ("z",))
    recs = {}
    with mesh:
        for L in ls:
            d = _slice_dims(L)
            rec = plan_7b._compile_variant(d, mesh, "s3", "full", batch, seq)
            recs[L] = {"L": L, "per_chip_live_gib": rec["per_chip_live_gib"],
                       "per_chip_bytes": rec["per_chip_bytes"]}
            print(f"[slice7b] AOT L={L}: {rec['per_chip_live_gib']} "
                  f"GiB/chip", flush=True)
    return recs


def run(n_mesh, seq, steps, n_devices, batch, full_l=32,
        skip_execute=False):
    try:
        with open(OUT) as f:
            prev = json.load(f)
    except (OSError, json.JSONDecodeError):
        prev = {}
    ex = None
    if skip_execute:
        # reuse a prior run's executed records (the expensive leg) when
        # only the AOT memory fit changed
        prior = {r.get("L"): r
                 for r in prev.get("slice_7b", {}).get("executed", [])}
        if 1 in prior and 2 in prior:
            ex = prior
    if ex is None:
        ex = _measure_execute(n_mesh, seq, steps)
    mem = _measure_memory(n_devices, batch, seq=2048)

    executed_ok = bool(ex[1]["ok"] and ex[2]["ok"])
    t1, t2 = ex[1]["t_step_s"], ex[2]["t_step_s"]
    t_layer = t2 - t1
    t_embed = t1 - t_layer
    # least-squares linear fit live(L) = m_base + L * m_layer over the
    # compiled depths (L >= 2; see _measure_memory on why L=1 is out)
    import numpy as _np
    xs = _np.array(sorted(mem))
    ys = _np.array([mem[L]["per_chip_live_gib"] for L in sorted(mem)])
    m_layer, m_base = _np.polyfit(xs, ys, 1)
    extrap_mem = m_base + full_l * m_layer

    full = next((v for v in prev.get("variants", [])
                 if v.get("name") == "s3_full"), None)
    recorded = full["per_chip_live_gib"] if full else None

    slice_rec = {
        "dims": "true 7B layer: H=4096 I=11008 heads=32 head_dim=128 "
                "V=32000; s3_full sharding, full remat",
        "backend": "cpu (1-core host; timings are execution evidence + "
                   "linearity structure, not TPU predictions)",
        "ok": executed_ok,
        "executed": list(ex.values()),
        "per_layer_step_s": round(t_layer, 3),
        "embed_logits_residue_s": round(t_embed, 3),
        "extrapolated_32L_step_s": round(t_embed + full_l * t_layer, 2),
        "aot_memory_batch16_seq2048": list(mem.values()),
        "per_layer_live_gib": round(float(m_layer), 4),
        "base_live_gib": round(float(m_base), 4),
        "extrapolated_32L_live_gib": round(float(extrap_mem), 3),
        "recorded_full_32L_live_gib": recorded,
        "linear_extrapolation_error_gib":
            round(float(extrap_mem) - recorded, 3) if recorded else None,
    }
    if not executed_ok:
        # a diverged slice must not masquerade as clean extrapolation
        # evidence: keep the raw records, drop the derived numbers
        for k in ("per_layer_step_s", "embed_logits_residue_s",
                  "extrapolated_32L_step_s"):
            slice_rec[k] = None
    prev["slice_7b"] = slice_rec
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(prev, f, indent=1)
        f.write("\n")
    os.replace(tmp, OUT)
    print(json.dumps({k: slice_rec[k] for k in
                      ("ok", "per_layer_step_s", "extrapolated_32L_live_gib",
                       "recorded_full_32L_live_gib",
                       "linear_extrapolation_error_gib")}))
    return slice_rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--inproc", action="store_true")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--mesh", type=int, default=8)
    ap.add_argument("--devices", type=int, default=16)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--skip-execute", action="store_true")
    args = ap.parse_args()

    if not args.inproc:
        import subprocess
        sys.path.insert(0, REPO)
        import __graft_entry__ as graft
        env = dict(os.environ)
        graft.force_cpu_env(env, args.devices)
        graft.strip_axon_pythonpath(env)
        cmd = [sys.executable, os.path.abspath(__file__), "--inproc",
               "--seq", str(args.seq), "--steps", str(args.steps),
               "--mesh", str(args.mesh), "--devices", str(args.devices),
               "--batch", str(args.batch)]
        if args.skip_execute:
            cmd.append("--skip-execute")
        return subprocess.run(cmd, env=env, cwd=REPO, timeout=3600).returncode

    run(args.mesh, args.seq, args.steps, args.devices, args.batch,
        skip_execute=args.skip_execute)
    return 0


if __name__ == "__main__":
    sys.exit(main())
