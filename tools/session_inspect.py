#!/usr/bin/env python
"""Offline session-manifest inspector: the durable-resume audit tool.

    python tools/session_inspect.py /path/to/session_root
    python tools/session_inspect.py /path/to/session_root --json
    python tools/session_inspect.py --selftest

Walks every ``*.json`` manifest a ``SessionStore`` published under the
root and re-verifies the whole durability contract with nothing but the
stdlib: the whole-document crc32, every per-block crc32 (over the
block's packed little-endian int64 token bytes), and a from-scratch
recompute of the ordered chain hashes (``blake2b(digest_size=8)`` over
``parent_hash_8B_le || token_bytes``) against the recorded entries.
``.tmp`` debris — a publish that crashed between the temp write and the
``os.replace`` — is reported as torn. Exit codes: 0 every manifest is
sound, 2 at least one is torn/corrupt/drifted, 1 usage or I/O error.

Deliberately stdlib-only (``struct.pack("<q", t)`` reproduces
``np.asarray(tokens, np.int64).tobytes()`` byte-for-byte): this is the
tool an operator runs on the shared session volume from a box with no
numpy/jax, and the lint lane imports it under the same constraint.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import struct
import sys
import zlib


def pack_tokens(tokens) -> bytes:
    """Little-endian int64 token bytes — what the store's CRCs and
    chain hashes consumed."""
    return b"".join(struct.pack("<q", int(t)) for t in tokens)


def chain_hashes(tokens, block_size: int):
    """Recompute the ordered chain hashes for ``tokens`` exactly as
    ``inference.prefix_cache.chain_hashes`` does, stdlib-only."""
    out = []
    parent = 0
    for i in range(len(tokens) // block_size):
        blk = tokens[i * block_size:(i + 1) * block_size]
        h = hashlib.blake2b(parent.to_bytes(8, "little")
                            + pack_tokens(blk), digest_size=8)
        parent = int.from_bytes(h.digest(), "little")
        out.append(parent)
    return out


def inspect_manifest(path: str) -> dict:
    """One manifest file -> {path, session, ok, reason, blocks, tokens}."""
    out = {"path": path, "session": None, "ok": True, "reason": "",
           "blocks": 0, "tokens": 0}

    def bad(reason):
        out["ok"] = False
        out["reason"] = reason
        return out

    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read())
    except (OSError, ValueError) as e:
        return bad(f"unreadable: {e}")
    out["session"] = doc.get("session_id")
    body = {k: v for k, v in doc.items() if k != "crc"}
    want = zlib.crc32(json.dumps(body, sort_keys=True).encode()) \
        & 0xFFFFFFFF
    if doc.get("crc") != want:
        return bad(f"document checksum mismatch "
                   f"({doc.get('crc')} != {want})")
    tokens = doc.get("tokens", [])
    bs = int(doc.get("block_size", 0) or 0)
    if bs < 1 or len(tokens) != doc.get("n_tokens"):
        return bad("token count / block size fields inconsistent")
    out["tokens"] = len(tokens)
    chain = chain_hashes(tokens, bs)
    entries = doc.get("blocks", [])
    if len(entries) != len(chain):
        return bad(f"{len(entries)} block entries != {len(chain)} "
                   f"full blocks")
    for i, (h, entry) in enumerate(zip(chain, entries)):
        blk = tokens[i * bs:(i + 1) * bs]
        crc = zlib.crc32(pack_tokens(blk)) & 0xFFFFFFFF
        if entry.get("crc") != crc:
            return bad(f"block {i} checksum mismatch "
                       f"({entry.get('crc')} != {crc})")
        if entry.get("h") != f"{h:016x}":
            return bad(f"block {i} chain-hash drift "
                       f"({entry.get('h')} != {h:016x})")
        out["blocks"] += 1
    return out


def inspect_root(root: str) -> dict:
    reports = []
    torn = []
    for name in sorted(os.listdir(root)):
        full = os.path.join(root, name)
        if name.endswith(".json.tmp"):
            torn.append({"path": full, "session": None, "ok": False,
                         "reason": "torn publish: .tmp debris (crash "
                                   "between write and rename)",
                         "blocks": 0, "tokens": 0})
        elif name.endswith(".json"):
            reports.append(inspect_manifest(full))
    reports.extend(torn)
    return {"root": root,
            "manifests": reports,
            "ok": all(r["ok"] for r in reports),
            "sound": sum(1 for r in reports if r["ok"])}


def print_table(report: dict) -> None:
    print(f"session root: {report['root']}")
    if not report["manifests"]:
        print("  (no manifests)")
        return
    print(f"  {'file':44} {'session':16} {'blocks':>6} {'tokens':>6}"
          f"  status")
    for r in report["manifests"]:
        status = "OK" if r["ok"] else f"BAD: {r['reason']}"
        print(f"  {os.path.basename(r['path']):44} "
              f"{str(r['session']):16} {r['blocks']:>6} "
              f"{r['tokens']:>6}  {status}")
    print(f"  sound manifests: {report['sound']}"
          f"/{len(report['manifests'])}")


def _selftest() -> int:
    """Build a synthetic root (sound, torn, doc-corrupt, entry-corrupt)
    with nothing but the stdlib, then check every verdict."""
    import tempfile

    def encode(sid, tokens, bs):
        chain = chain_hashes(tokens, bs)
        blocks = [{"h": f"{h:016x}",
                   "crc": zlib.crc32(pack_tokens(
                       tokens[i * bs:(i + 1) * bs])) & 0xFFFFFFFF}
                  for i, h in enumerate(chain)]
        body = {"version": 1, "session_id": sid, "model": "m:00000000",
                "block_size": bs, "last_activity": 1.0,
                "n_tokens": len(tokens), "tokens": tokens,
                "blocks": blocks}
        body["crc"] = zlib.crc32(
            json.dumps(body, sort_keys=True).encode()) & 0xFFFFFFFF
        return json.dumps(body, sort_keys=True).encode()

    with tempfile.TemporaryDirectory(prefix="session_inspect_self_") \
            as root:
        tokens = [(7 * i + 3) % 101 for i in range(20)]
        with open(os.path.join(root, "good.00000001.json"), "wb") as f:
            f.write(encode("good", tokens, 4))
        with open(os.path.join(root, "torn.00000002.json.tmp"),
                  "wb") as f:
            f.write(encode("torn", tokens, 4)[:30])  # mid-write crash
        doc = json.loads(encode("bitrot", tokens, 4))
        doc["tokens"][3] ^= 1   # flip a token, keep every recorded crc
        with open(os.path.join(root, "bitrot.00000003.json"),
                  "wb") as f:
            f.write(json.dumps(doc, sort_keys=True).encode())
        rep = inspect_root(root)
        by_sid = {r["session"]: r for r in rep["manifests"]
                  if r["session"]}
        assert by_sid["good"]["ok"] and by_sid["good"]["blocks"] == 5, \
            by_sid["good"]
        assert not by_sid["bitrot"]["ok"] \
            and "checksum" in by_sid["bitrot"]["reason"], by_sid["bitrot"]
        torn = [r for r in rep["manifests"] if r["path"].endswith(".tmp")]
        assert torn and "torn" in torn[0]["reason"], torn
        assert not rep["ok"] and rep["sound"] == 1, rep
        # entry-level corruption: keep the doc crc honest but drift one
        # block's recorded chain hash
        doc = json.loads(encode("drift", tokens, 4))
        doc["blocks"][2]["h"] = "0" * 16
        body = {k: v for k, v in doc.items() if k != "crc"}
        doc["crc"] = zlib.crc32(
            json.dumps(body, sort_keys=True).encode()) & 0xFFFFFFFF
        p = os.path.join(root, "drift.00000004.json")
        with open(p, "wb") as f:
            f.write(json.dumps(doc, sort_keys=True).encode())
        r = inspect_manifest(p)
        assert not r["ok"] and "drift" in r["reason"], r
    print("session_inspect selftest OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", help="session store root")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="validate the inspector against a synthetic "
                         "root and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.root:
        ap.error("root is required (or --selftest)")
    if not os.path.isdir(args.root):
        print(f"error: {args.root!r} is not a directory",
              file=sys.stderr)
        return 1
    report = inspect_root(args.root)
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        print_table(report)
    return 0 if report["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
