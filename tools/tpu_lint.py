#!/usr/bin/env python
"""Trace-safety linter CLI (TS* rules of paddle_tpu.analysis).

    python tools/tpu_lint.py paddle_tpu examples            # text report
    python tools/tpu_lint.py --json paddle_tpu              # machine output
    python tools/tpu_lint.py --write-baseline paddle_tpu examples
    python tools/tpu_lint.py --audit-ops                    # DF006 registry audit

Exit status: 0 when no ERROR-severity findings survive suppressions and
the baseline; 1 otherwise. Warnings are reported but never fail the run
(use --strict to fail on warnings too).

Deliberately does NOT import the paddle_tpu package (and therefore not
jax): the rule engine (analysis/ast_lint.py, analysis/findings.py) is
stdlib-only and loaded straight off the source tree, so the tier-1 lint
gate runs in a couple of seconds. --audit-ops is the exception — it
imports the live op registry.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_ANALYSIS_DIR = os.path.join(_REPO, "paddle_tpu", "analysis")
sys.path.insert(0, _ANALYSIS_DIR)

import ast_lint      # noqa: E402  (stdlib-only modules, loaded directly)
import findings as findings_mod  # noqa: E402

DEFAULT_BASELINE = os.path.join(_HERE, "tpu_lint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_lint",
        description="paddle_tpu trace-safety linter (TS rules)")
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of accepted findings "
                         "(default: tools/tpu_lint_baseline.json; "
                         "pass 'none' to disable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline "
                         "file and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule IDs to restrict to")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--audit-ops", action="store_true",
                    help="also run the DF006 inplace/donation alias audit "
                         "over the live op registry (imports paddle_tpu)")
    args = ap.parse_args(argv)

    if not args.paths and not args.audit_ops:
        ap.error("no paths given")

    paths = [p if os.path.isabs(p) else os.path.join(os.getcwd(), p)
             for p in args.paths]
    results = ast_lint.lint_paths(paths, root=os.getcwd())

    if args.audit_ops:
        sys.path.insert(0, _REPO)
        from paddle_tpu.analysis import audit_inplace_aliases
        results.extend(audit_inplace_aliases())

    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        results = [f for f in results if f.rule in wanted]

    if args.write_baseline:
        path = (args.baseline if args.baseline.lower() != "none"
                else DEFAULT_BASELINE)
        findings_mod.write_baseline(results, path)
        print(f"wrote {len(results)} finding(s) to {path}")
        return 0

    if args.baseline.lower() != "none":
        baseline = findings_mod.load_baseline(args.baseline)
        if baseline:
            results = findings_mod.apply_baseline(results, baseline)

    if args.json:
        print(json.dumps({"findings": [f.to_dict() for f in results],
                          "summary": findings_mod.summarize(results)},
                         indent=2))
    else:
        for f in results:
            print(f)
        print(findings_mod.summarize(results))

    if findings_mod.has_errors(results):
        return 1
    if args.strict and results:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
