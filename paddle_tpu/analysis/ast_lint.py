"""Python AST trace-safety linter (TS* rules).

Scans source for the jit-context hazards that burn TPU users, informed by
the graph-break/mutation hooks in ``jit/sot.py`` (bool/int/float/item/numpy
materializations are the breaks; outer-state mutation is the bake-in):

* TS101  host sync on a traced value inside a @jit/@to_static function
* TS102  data-dependent python if/while on a traced value
* TS103  jax.jit / to_static constructed inside a loop
* TS104  side effects during trace (print of traced values, outer-state
         mutation, Tensor._set_data)
* TS105  fresh array/tensor literal built in an enclosing function and
         captured by a nested @jit/to_static closure — each rebuild
         hashes as a new constant and silently recompiles per call

Heuristic taint model: function parameters are assumed traced unless they
carry a python-literal default or an int/bool/str annotation (static config
by convention); any name assigned from an expression that reads a tainted
name becomes tainted. No cross-function propagation — this is a linter,
not an abstract interpreter; precision tuning happens through inline
``# tpu-lint: disable=RULE`` suppressions and the checked-in baseline.

Stdlib-only on purpose: ``tools/tpu_lint.py`` imports this file directly
(without the ``paddle_tpu`` package, so without jax) to stay fast.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

try:
    from .findings import (Finding, is_suppressed, parse_suppressions)
except ImportError:  # standalone import by tools/tpu_lint.py
    from findings import (Finding, is_suppressed,  # type: ignore
                          parse_suppressions)

__all__ = ["lint_source", "lint_file", "lint_paths", "iter_py_files"]

# decorator spellings that put a function body into a trace context
_TRACED_SUFFIXES = (".to_static", ".jit")
_TRACED_EXACT = {"jit", "to_static"}
_NOT_TRACED = {"not_to_static"}

# jit-constructor spellings for TS103
_JIT_CTORS_EXACT = {"to_static", "jit"}
_JIT_CTOR_SUFFIXES = (".to_static", "jax.jit")

_HOST_SYNC_ATTRS = {"item", "numpy", "tolist"}
_HOST_SYNC_BUILTINS = {"float", "int", "bool"}
_HOST_SYNC_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_STATIC_ANNOTATIONS = {"int", "bool", "str"}

# array/tensor constructors whose result hashes as a fresh jit constant
# every time it is rebuilt (TS105)
_FRESH_ARRAY_FNS = {"array", "asarray", "ones", "zeros", "full", "arange",
                    "eye", "linspace", "tril", "triu"}
_FRESH_ARRAY_BASES = {"np", "numpy", "jnp"}


def _dotted(node) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def _names_in(node) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_traced_decorator(dec) -> bool:
    target = dec.func if isinstance(dec, ast.Call) else dec
    name = _dotted(target)
    if not name:
        return False
    last = name.rsplit(".", 1)[-1]
    if last in _NOT_TRACED:
        return False
    return name in _TRACED_EXACT or any(
        name.endswith(s) for s in _TRACED_SUFFIXES)


def _is_jit_ctor(call: ast.Call) -> bool:
    name = _dotted(call.func)
    if not name:
        return False
    return (name in _JIT_CTORS_EXACT
            or any(name.endswith(s) for s in _JIT_CTOR_SUFFIXES))


def _is_fresh_array_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    if not name:
        return False
    parts = name.split(".")
    if parts[-1] == "to_tensor":
        return True
    if len(parts) >= 2 and parts[-1] in _FRESH_ARRAY_FNS:
        return (parts[0] in _FRESH_ARRAY_BASES
                or ".".join(parts[:-1]).endswith("jax.numpy"))
    return False


def _initial_taint(fn: ast.FunctionDef) -> Set[str]:
    """Params assumed traced, minus literal-defaulted / static-annotated."""
    a = fn.args
    params = list(a.posonlyargs) + list(a.args)
    taint = set()
    n_def = len(a.defaults)
    defaulted = {p.arg for p in params[len(params) - n_def:]} if n_def else set()
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            defaulted.add(p.arg)
    for p in params + list(a.kwonlyargs):
        if p.arg in ("self", "cls"):
            continue
        if p.arg in defaulted:
            continue
        ann = getattr(p, "annotation", None)
        if ann is not None and _dotted(ann) in _STATIC_ANNOTATIONS:
            continue
        taint.add(p.arg)
    if a.vararg:
        taint.add(a.vararg.arg)
    return taint


class _TracedBodyLinter(ast.NodeVisitor):
    """Lints one traced function body with a flow-insensitive taint pass."""

    def __init__(self, fn: ast.FunctionDef, path: str,
                 src_lines: Sequence[str]):
        self.fn = fn
        self.path = path
        self.src_lines = src_lines
        self.taint = _initial_taint(fn)
        self.local_defs = set(self.taint)
        self.findings: List[Finding] = []

    # -- helpers ------------------------------------------------------------
    def _line_text(self, node) -> str:
        ln = getattr(node, "lineno", 0)
        return self.src_lines[ln - 1] if 0 < ln <= len(self.src_lines) else ""

    def _emit(self, rule: str, node, message: str):
        self.findings.append(Finding(
            rule=rule, message=message, file=self.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            source_line=self._line_text(node)))

    def _tainted(self, expr) -> bool:
        return bool(_names_in(expr) & self.taint)

    # -- taint propagation ---------------------------------------------------
    def _note_assign(self, targets, value):
        names = set()
        for t in targets:
            names |= {n.id for n in ast.walk(t) if isinstance(n, ast.Name)}
        self.local_defs |= names
        if value is not None and self._tainted(value):
            self.taint |= names

    def visit_Assign(self, node):
        self._note_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._note_assign([node.target], node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._note_assign([node.target], node.value)
        self.generic_visit(node)

    def visit_For(self, node):
        if self._tainted(node.iter):
            self._note_assign([node.target], node.iter)
        else:
            self._note_assign([node.target], None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # nested defs share the trace context; their params join the taint
        if node is not self.fn:
            self.taint |= _initial_taint(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- rules ---------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if (func.attr in _HOST_SYNC_ATTRS
                    and self._tainted(func.value)):
                self._emit("TS101", node,
                           f".{func.attr}() on a traced value forces a "
                           "host sync inside the jit context")
            elif func.attr == "_set_data" and self._tainted(func.value):
                self._emit("TS104", node,
                           "Tensor._set_data during trace rebinds the "
                           "buffer at trace time only")
            elif (func.attr in ("append", "extend", "update", "add")
                  and isinstance(func.value, ast.Name)
                  and func.value.id not in self.local_defs):
                self._emit("TS104", node,
                           f"mutating enclosing-scope '{func.value.id}' "
                           "during trace happens once at trace time, not "
                           "per call")
        else:
            name = _dotted(func)
            if (name in _HOST_SYNC_BUILTINS and node.args
                    and self._tainted(node.args[0])):
                self._emit("TS101", node,
                           f"{name}() on a traced value materializes it "
                           "on host (graph break / ConcretizationTypeError)")
            elif (name in _HOST_SYNC_NP and node.args
                  and self._tainted(node.args[0])):
                self._emit("TS101", node,
                           f"{name}() on a traced value pulls it to host "
                           "memory inside the jit context")
            elif name == "print" and any(self._tainted(a)
                                         for a in node.args):
                self._emit("TS104", node,
                           "print of a traced value runs at trace time "
                           "only; use jax.debug.print / callbacks")
        self.generic_visit(node)

    def _check_control(self, node, kind: str):
        test = node.test
        # isinstance()/hasattr() tests are static dispatch, not data flow
        if isinstance(test, ast.Call) and _dotted(test.func) in (
                "isinstance", "hasattr", "callable"):
            return
        if self._tainted(test):
            self._emit("TS102", node,
                       f"python '{kind}' on a traced value; use lax.cond/"
                       "jnp.where, or accept the SOT graph break knowingly")

    def visit_If(self, node):
        self._check_control(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_control(node, "while")
        self.generic_visit(node)

    def visit_Global(self, node):
        self._emit("TS104", node,
                   "global statement inside a traced function: the "
                   "mutation runs at trace time only")

    def visit_Nonlocal(self, node):
        self._emit("TS104", node,
                   "nonlocal statement inside a traced function: the "
                   "mutation runs at trace time only")


class _ModuleLinter(ast.NodeVisitor):
    """Module-wide rules: traced-function discovery + TS103."""

    def __init__(self, path: str, src_lines: Sequence[str]):
        self.path = path
        self.src_lines = src_lines
        self.findings: List[Finding] = []
        #: finding-id -> alt suppression lines (enclosing def/decorator)
        self.alt_lines: Dict[int, Tuple[int, ...]] = {}
        self._loop_depth = 0

    def _line_text(self, node) -> str:
        ln = getattr(node, "lineno", 0)
        return self.src_lines[ln - 1] if 0 < ln <= len(self.src_lines) else ""

    def visit_FunctionDef(self, node):
        if any(_is_traced_decorator(d) for d in node.decorator_list):
            sub = _TracedBodyLinter(node, self.path, self.src_lines)
            sub.visit(node)
            alts = tuple({node.lineno,
                          *(d.lineno for d in node.decorator_list)})
            for f in sub.findings:
                self.alt_lines[id(f)] = alts
            self.findings.extend(sub.findings)
            # don't descend again: the body linter already walked it,
            # but TS103 loops inside still need a look
        self._check_fresh_capture(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- TS105: fresh array built here, captured by a nested traced fn ----
    def _check_fresh_capture(self, node):
        # array-ctor assignments in node's OWN scope (nested scopes are
        # checked when their def is visited)
        assigns: Dict[str, ast.Assign] = {}
        stack = list(node.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(n, ast.Assign) and _is_fresh_array_call(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        assigns[t.id] = n
            stack.extend(ast.iter_child_nodes(n))
        if not assigns:
            return

        local_defs = {d.name: d for d in ast.walk(node)
                      if isinstance(d, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and d is not node}
        traced = [d for d in local_defs.values()
                  if any(_is_traced_decorator(dec)
                         for dec in d.decorator_list)]
        for call in ast.walk(node):
            if (isinstance(call, ast.Call) and _is_jit_ctor(call)
                    and call.args and isinstance(call.args[0], ast.Name)
                    and call.args[0].id in local_defs):
                d = local_defs[call.args[0].id]
                if d not in traced:
                    traced.append(d)

        seen = set()
        for g in traced:
            a = g.args
            bound = {p.arg for p in (list(a.posonlyargs) + list(a.args)
                                     + list(a.kwonlyargs))}
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            for n in ast.walk(g):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
                elif isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) and n is not g:
                    bound.add(n.name)
            loads = {n.id for n in ast.walk(g)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)}
            for name in sorted((loads - bound) & set(assigns)):
                an = assigns[name]
                if (name, an.lineno) in seen:
                    continue
                seen.add((name, an.lineno))
                f = Finding(
                    rule="TS105",
                    message=f"fresh array '{name}' "
                            f"({_dotted(an.value.func)}) built in "
                            f"'{node.name}' is captured by jit-traced "
                            f"'{g.name}': every call rebuilds it and the "
                            "new constant silently recompiles — hoist it "
                            "to module scope or pass it as an argument",
                    file=self.path, line=an.lineno, col=an.col_offset,
                    source_line=self._line_text(an))
                self.alt_lines[id(f)] = (node.lineno, g.lineno)
                self.findings.append(f)

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _visit_loop

    def visit_Call(self, node):
        if self._loop_depth and _is_jit_ctor(node):
            ln = node.lineno
            text = (self.src_lines[ln - 1]
                    if 0 < ln <= len(self.src_lines) else "")
            self.findings.append(Finding(
                rule="TS103",
                message=f"'{_dotted(node.func)}(...)' constructed inside "
                        "a loop: every iteration builds (and may compile) "
                        "a fresh callable; hoist it out",
                file=self.path, line=ln, col=node.col_offset,
                source_line=text))
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>",
                apply_suppressions: bool = True) -> List[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="TS101", severity="error",
                        message=f"syntax error: {e.msg}", file=path,
                        line=e.lineno or 0)]
    src_lines = source.splitlines()
    linter = _ModuleLinter(path, src_lines)
    linter.visit(tree)
    findings = linter.findings
    if apply_suppressions:
        per_line, file_wide = parse_suppressions(source)
        findings = [f for f in findings
                    if not is_suppressed(f, per_line, file_wide,
                                         linter.alt_lines.get(id(f), ()))]
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    with open(path, encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    return lint_source(source, rel)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(out)


def lint_paths(paths: Iterable[str],
               root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path, root=root))
    return findings
