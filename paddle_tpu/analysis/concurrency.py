"""Concurrency-safety lint (CC* rules): whole-repo lock-acquisition
graph over stdlib ``ast`` — no jax import, loaded standalone by
``tools/race_check.py`` exactly like ``ast_lint``.

The model: every named lock in the corpus gets a stable identity —
module-level ``NAME = threading.Lock()`` becomes ``mod.py::NAME``,
``self.NAME = threading.Lock()`` (or TracedLock/RLock/Condition) inside
class ``C`` becomes ``C.NAME``. Each function is walked with a
held-locks stack (``with lock:`` spans, plus coarse ``.acquire()``/
``.release()`` pairs); what a function *may* acquire is propagated
through a heuristically-resolved call graph (self-methods through the
class and its corpus bases, bare names through the module, otherwise a
globally-unique method name) to a fixpoint. From that:

* CC401 lock-order-cycle — the same pair of locks observed in both
  orders at two sites (directly or through calls).
* CC402 blocking-call-under-lock — sleep / thread join / device_put /
  block_until_ready / future result / event wait / queue.get / file IO
  while at least one named lock is held (one call-graph level deep).
  ``cond.wait()`` while holding ``cond`` itself is exempt.
* CC403 lock-held-across-callback — a parameter / ``on_*`` /
  ``*_callbacks`` / ``*hooks`` callable invoked with a lock held.
* CC404 unguarded-shared-mutation — an attribute written under a lock
  at one site and with no lock at another (outside __init__).

CC405/CC406 are the runtime witness rules (``utils/locks.py``); this
module only *audits* their JSON dumps (:func:`audit_witness`), so chaos
drill artifacts can be checked offline by ``race_check --witness``.

Heuristic by design — precision tuning happens through inline
``# tpu-lint: disable=CC402`` suppressions and the checked-in
``tools/race_check_baseline.json``.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

try:
    from .findings import Finding, is_suppressed, parse_suppressions
    from .ast_lint import iter_py_files, _dotted
except ImportError:  # standalone import by tools/race_check.py
    from findings import (Finding, is_suppressed,  # type: ignore
                          parse_suppressions)
    from ast_lint import iter_py_files, _dotted  # type: ignore

__all__ = ["analyze_source", "analyze_sources", "analyze_paths",
           "audit_witness", "audit_witness_paths"]

# -- lock identification ------------------------------------------------------

_LOCK_CTOR_LAST = {"Lock", "RLock", "TracedLock", "TracedRLock", "Condition"}
_LOCKISH_RE = re.compile(r"lock|mutex|cond|sem", re.I)
_THREADISH_RE = re.compile(r"thread|proc|worker", re.I)
_EVENTISH_RE = re.compile(r"ev$|event|cond|done|ready|stop|barrier", re.I)
_CB_RE = re.compile(r"^on_[a-z0-9_]*$|callbacks?$|hooks?$|_cb$|^cb$|"
                    r"^callback$|^hook$")
_INIT_METHODS = {"__init__", "__post_init__", "__new__"}

#: dotted-call bases that are never corpus functions — unique-method-name
#: resolution must not claim os.path.join for a repo method named 'join'
_STDLIBISH = {"os", "sys", "time", "json", "np", "numpy", "jax", "jnp",
              "threading", "queue", "queue_mod", "shutil", "pickle", "re",
              "math", "random", "logging", "subprocess", "socket", "struct",
              "collections", "itertools", "functools", "ast", "io", "ctypes",
              "hashlib", "zlib", "tempfile", "warnings", "signal"}


def _is_lock_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    return bool(name) and name.rsplit(".", 1)[-1] in _LOCK_CTOR_LAST


def _blocking_op(node: ast.Call) -> Optional[str]:
    """Dotted name of a blocking operation, or None."""
    name = _dotted(node.func)
    if not name:
        return None
    last = name.rsplit(".", 1)[-1]
    base = name.rsplit(".", 1)[0] if "." in name else ""
    kwargs = {k.arg for k in node.keywords if k.arg}
    if name in ("time.sleep", "sleep", "open", "os.fsync", "os.replace",
                "json.dump", "pickle.dump"):
        return name
    if last in ("device_put", "block_until_ready"):
        return name
    if last == "result":
        return name
    if last == "join":
        if "timeout" in kwargs or _THREADISH_RE.search(base):
            return name
        return None
    if last == "wait":
        if "timeout" in kwargs or _EVENTISH_RE.search(base):
            return name
        return None
    if last == "get":
        lb = base.rsplit(".", 1)[-1]
        if lb == "q" or lb.endswith("_q") or "queue" in lb.lower():
            return name
        return None
    return None


# -- corpus model -------------------------------------------------------------

class _ClassInfo:
    __slots__ = ("name", "bases", "lock_attrs", "methods")

    def __init__(self, name: str, bases: List[str]):
        self.name = name
        self.bases = bases
        self.lock_attrs: Set[str] = set()     # attrs assigned a lock ctor
        self.methods: Dict[str, str] = {}     # method name -> func qualname


class _FuncInfo:
    __slots__ = ("qualname", "modkey", "cls", "name", "lineno", "params",
                 "acquires", "calls", "blocking", "blocking_direct",
                 "callback_calls", "attr_writes")

    def __init__(self, qualname, modkey, cls, name, lineno, params):
        self.qualname = qualname
        self.modkey = modkey
        self.cls = cls                        # _ClassInfo or None
        self.name = name
        self.lineno = lineno
        self.params = params                  # set of parameter names
        #: (lock_id, line, held_tuple)
        self.acquires: List[Tuple[str, int, tuple]] = []
        #: (callee_dotted, line, held_tuple, is_self_call)
        self.calls: List[Tuple[str, int, tuple, bool]] = []
        #: (op_name, line, held_tuple) — held nonempty
        self.blocking: List[Tuple[str, int, tuple]] = []
        #: blocking op names anywhere in the body (for 1-level propagation)
        self.blocking_direct: Set[str] = set()
        #: (callee_text, line, held_tuple) — held nonempty
        self.callback_calls: List[Tuple[str, int, tuple]] = []
        #: (attr, line, held_tuple) — self.attr stores
        self.attr_writes: List[Tuple[str, int, tuple]] = []


class _Corpus:
    def __init__(self):
        self.sources: Dict[str, str] = {}
        self.lines: Dict[str, List[str]] = {}
        self.module_locks: Dict[str, Set[str]] = {}      # modkey -> names
        self.classes: Dict[str, _ClassInfo] = {}         # class name -> info
        self.functions: Dict[str, _FuncInfo] = {}        # qualname -> info
        self.mod_funcs: Dict[Tuple[str, str], str] = {}  # (modkey,nm)->qn
        self.by_name: Dict[str, List[str]] = {}          # nm -> [qualnames]

    def line_text(self, modkey: str, ln: int) -> str:
        lines = self.lines.get(modkey, ())
        return lines[ln - 1] if 0 < ln <= len(lines) else ""

    def class_lock_attr(self, cls: Optional[_ClassInfo],
                        attr: str) -> Optional[str]:
        """Resolve self.<attr> to the defining class's lock id, walking
        corpus bases."""
        seen = set()
        stack = [cls] if cls is not None else []
        while stack:
            c = stack.pop()
            if c is None or c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.lock_attrs:
                return f"{c.name}.{attr}"
            stack.extend(self.classes.get(b) for b in c.bases)
        return None

    def resolve_call(self, info: _FuncInfo, callee: str,
                     is_self: bool) -> Optional[str]:
        last = callee.rsplit(".", 1)[-1]
        if callee.split(".", 1)[0] in _STDLIBISH:
            return None
        if is_self and info.cls is not None:
            seen: Set[str] = set()
            stack = [info.cls]
            while stack:
                c = stack.pop()
                if c is None or c.name in seen:
                    continue
                seen.add(c.name)
                if last in c.methods:
                    return c.methods[last]
                stack.extend(self.classes.get(b) for b in c.bases)
            return None
        if "." not in callee:
            qn = self.mod_funcs.get((info.modkey, callee))
            if qn:
                return qn
        cands = self.by_name.get(last, ())
        if len(cands) == 1:
            return cands[0]
        return None


# -- per-function walker ------------------------------------------------------

class _BodyWalker(ast.NodeVisitor):
    """Walks one function body tracking the held-locks stack."""

    def __init__(self, corpus: _Corpus, info: _FuncInfo):
        self.corpus = corpus
        self.info = info
        self.held: List[Tuple[str, int]] = []   # (lock_id, acquire line)
        self.local_locks: Set[str] = set()      # local vars bound to locks
        self.cb_vars: Set[str] = set()          # loop vars over callbacks

    # -- lock-expression resolution ---------------------------------------
    def _lock_id(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id in self.corpus.module_locks.get(self.info.modkey, ()):
                return f"{self.info.modkey}::{expr.id}"
            if expr.id in self.local_locks:
                return f"{self.info.qualname}::{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            base = _dotted(expr.value)
            attr = expr.attr
            if base == "self":
                rid = self.corpus.class_lock_attr(self.info.cls, attr)
                if rid:
                    return rid
                if _LOCKISH_RE.search(attr):
                    cname = self.info.cls.name if self.info.cls else "?"
                    return f"{cname}.{attr}"
                return None
            # non-self attribute: unique defining class, else lockish name
            owners = [c.name for c in self.corpus.classes.values()
                      if attr in c.lock_attrs]
            if len(owners) == 1:
                return f"{owners[0]}.{attr}"
            if _LOCKISH_RE.search(attr):
                return f"*.{attr}"
        return None

    def _held_ids(self) -> tuple:
        return tuple(h[0] for h in self.held)

    # -- visitors ----------------------------------------------------------
    def visit_With(self, node: ast.With):
        pushed = 0
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                self.info.acquires.append(
                    (lid, item.context_expr.lineno, self._held_ids()))
                self.held.append((lid, item.context_expr.lineno))
                pushed += 1
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_For(self, node: ast.For):
        it = _dotted(node.iter)
        if it and _CB_RE.search(it.rsplit(".", 1)[-1]):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    self.cb_vars.add(n.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.local_locks.add(t.id)
        self._note_attr_writes(node.targets, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._note_attr_writes([node.target], node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._note_attr_writes([node.target], node.lineno)
        self.generic_visit(node)

    def _note_attr_writes(self, targets, line: int):
        for t in targets:
            for n in ast.walk(t):
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.ctx, (ast.Store,))
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"
                        and not _LOCKISH_RE.search(n.attr)):
                    self.info.attr_writes.append(
                        (n.attr, line, self._held_ids()))

    def visit_Call(self, node: ast.Call):
        held = self._held_ids()
        name = _dotted(node.func)
        if name:
            last = name.rsplit(".", 1)[-1]
            base = name.rsplit(".", 1)[0] if "." in name else ""
            # explicit .acquire(): coarse — held until .release() or end
            if last == "acquire" and isinstance(node.func, ast.Attribute):
                lid = self._lock_id(node.func.value)
                if lid is not None:
                    self.info.acquires.append((lid, node.lineno, held))
                    self.held.append((lid, node.lineno))
            elif last == "release" and isinstance(node.func, ast.Attribute):
                lid = self._lock_id(node.func.value)
                if lid is not None:
                    for i in range(len(self.held) - 1, -1, -1):
                        if self.held[i][0] == lid:
                            del self.held[i]
                            break
            op = _blocking_op(node)
            if op is not None:
                self.info.blocking_direct.add(op)
                if held:
                    # cond.wait() while holding cond itself is the normal
                    # condition-variable protocol, not a CC402
                    base_lid = (self._lock_id(node.func.value)
                                if isinstance(node.func, ast.Attribute)
                                else None)
                    if base_lid is None or base_lid not in held:
                        self.info.blocking.append((op, node.lineno, held))
            if held and self._is_callback(node):
                self.info.callback_calls.append((name, node.lineno, held))
            is_self = name.startswith("self.")
            if last not in ("acquire", "release"):
                self.info.calls.append((name, node.lineno, held, is_self))
        self.generic_visit(node)

    def _is_callback(self, node: ast.Call) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            nm = func.id
            if nm in self.cb_vars:
                return True
            return nm in self.info.params and bool(_CB_RE.search(nm))
        if isinstance(func, ast.Attribute):
            return bool(_CB_RE.search(func.attr))
        return False


# -- corpus construction ------------------------------------------------------

def _collect_module(corpus: _Corpus, modkey: str, tree: ast.Module):
    corpus.module_locks.setdefault(modkey, set())
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    corpus.module_locks[modkey].add(t.id)

    def reg_func(fn, cls: Optional[_ClassInfo]):
        qual = (f"{modkey}::{cls.name}.{fn.name}" if cls
                else f"{modkey}::{fn.name}")
        a = fn.args
        params = {p.arg for p in (list(a.posonlyargs) + list(a.args)
                                  + list(a.kwonlyargs))} - {"self", "cls"}
        info = _FuncInfo(qual, modkey, cls, fn.name, fn.lineno, params)
        corpus.functions[qual] = info
        corpus.by_name.setdefault(fn.name, []).append(qual)
        if cls is None:
            corpus.mod_funcs[(modkey, fn.name)] = qual
        else:
            cls.methods.setdefault(fn.name, qual)
        return info

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            reg_func(node, None)
        elif isinstance(node, ast.ClassDef):
            cinfo = corpus.classes.setdefault(
                node.name,
                _ClassInfo(node.name, [_dotted(b).rsplit(".", 1)[-1]
                                       for b in node.bases if _dotted(b)]))
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    reg_func(sub, cinfo)
                elif isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            cinfo.lock_attrs.add(t.id)
            # self.X = Lock() anywhere in the class body
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Assign)
                        and _is_lock_ctor(sub.value)):
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            cinfo.lock_attrs.add(t.attr)


def _walk_functions(corpus: _Corpus, modkey: str, tree: ast.Module):
    def run(fn, cls):
        qual = (f"{modkey}::{cls.name}.{fn.name}" if cls
                else f"{modkey}::{fn.name}")
        info = corpus.functions.get(qual)
        if info is None:
            return
        walker = _BodyWalker(corpus, info)
        for stmt in fn.body:
            walker.visit(stmt)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            run(node, None)
        elif isinstance(node, ast.ClassDef):
            cinfo = corpus.classes.get(node.name)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    run(sub, cinfo)


def _named(lock_id: str) -> bool:
    """Module/class-level locks participate in cross-site analysis;
    function-local locks (``qualname::var``, two ``::``) are per-call
    and do not."""
    parts = lock_id.split("::")
    if len(parts) == 1:
        return True                       # class-attr lock: "C.attr"
    return len(parts) == 2 and parts[0].endswith(".py")


# -- the CC401..CC404 analyses ------------------------------------------------

def _fixpoint_acquires(corpus: _Corpus) -> Dict[str, Set[str]]:
    may: Dict[str, Set[str]] = {
        qn: {a[0] for a in f.acquires if _named(a[0])}
        for qn, f in corpus.functions.items()}
    for _ in range(24):
        changed = False
        for qn, f in corpus.functions.items():
            for callee, _, _, is_self in f.calls:
                target = corpus.resolve_call(f, callee, is_self)
                if target is None:
                    continue
                add = may.get(target, set()) - may[qn]
                if add:
                    may[qn] |= add
                    changed = True
        if not changed:
            break
    return may


def _analyze_corpus(corpus: _Corpus) -> List[Finding]:
    findings: List[Finding] = []
    may = _fixpoint_acquires(corpus)

    def modkey_of(qn: str) -> str:
        return qn.split("::", 1)[0]

    def emit(rule, modkey, line, message, **extra):
        findings.append(Finding(
            rule=rule, message=message, file=modkey, line=line,
            source_line=corpus.line_text(modkey, line),
            extra=extra or {}))

    # -- edge collection for CC401 ---------------------------------------
    #: (held, acquired) -> list of (modkey, line, via)
    edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}

    def add_edge(a, b, modkey, line, via=""):
        if a == b:
            return
        sites = edges.setdefault((a, b), [])
        if len(sites) < 8:
            sites.append((modkey, line, via))

    for qn, f in corpus.functions.items():
        mk = modkey_of(qn)
        for lid, line, held in f.acquires:
            if not _named(lid):
                continue
            for h in held:
                if _named(h):
                    add_edge(h, lid, mk, line)
        for callee, line, held, is_self in f.calls:
            if not held:
                continue
            target = corpus.resolve_call(f, callee, is_self)
            if target is None:
                continue
            for lid in may.get(target, ()):
                if lid in held:
                    continue
                for h in held:
                    if _named(h):
                        add_edge(h, lid, mk, line, via=callee)

    reported_pairs: Set[Tuple[str, str]] = set()
    for (a, b), sites in sorted(edges.items()):
        if (b, a) not in edges:
            continue
        pair = tuple(sorted((a, b)))
        if pair in reported_pairs:
            continue
        reported_pairs.add(pair)
        for (mk, line, via), (ra, rb) in (
                (sites[0], (a, b)), (edges[(b, a)][0], (b, a))):
            omk, oline, ovia = (edges[(rb, ra)][0])
            via_txt = f" (via {via})" if via else ""
            emit("CC401", mk, line,
                 f"lock order cycle: '{rb}' acquired while holding "
                 f"'{ra}'{via_txt}, but the opposite order is taken at "
                 f"{omk}:{oline}" + (f" (via {ovia})" if ovia else ""),
                 locks=list(pair))

    # -- CC402: blocking under lock (direct + one call level) ------------
    for qn, f in corpus.functions.items():
        mk = modkey_of(qn)
        for op, line, held in f.blocking:
            if not any(_named(h) for h in held):
                continue
            emit("CC402", mk, line,
                 f"blocking call '{op}' while holding "
                 f"{', '.join(repr(h) for h in held if _named(h))} — "
                 "every contender stalls for the full blocking latency",
                 op=op, locks=[h for h in held if _named(h)])
        for callee, line, held, is_self in f.calls:
            if not any(_named(h) for h in held):
                continue
            target = corpus.resolve_call(f, callee, is_self)
            if target is None or target == qn:
                continue
            t = corpus.functions[target]
            if t.blocking_direct:
                ops = ", ".join(sorted(t.blocking_direct))
                emit("CC402", mk, line,
                     f"call to '{callee}' under "
                     f"{', '.join(repr(h) for h in held if _named(h))} "
                     f"performs blocking op(s): {ops}",
                     op=ops, via=callee,
                     locks=[h for h in held if _named(h)])

    # -- CC403: callback invoked under lock ------------------------------
    for qn, f in corpus.functions.items():
        mk = modkey_of(qn)
        for callee, line, held in f.callback_calls:
            named_held = [h for h in held if _named(h)]
            if not named_held:
                continue
            emit("CC403", mk, line,
                 f"callback '{callee}' invoked while holding "
                 f"{', '.join(repr(h) for h in named_held)} — it can "
                 "re-enter the owner or block arbitrarily long",
                 callback=callee, locks=named_held)

    # -- CC404: unguarded shared mutation --------------------------------
    #: (class, attr) -> {"guarded": [...], "bare": [...]}
    writes: Dict[Tuple[str, str], Dict[str, list]] = {}
    for qn, f in corpus.functions.items():
        if f.cls is None:
            continue
        mk = modkey_of(qn)
        for attr, line, held in f.attr_writes:
            rec = writes.setdefault((f.cls.name, attr),
                                    {"guarded": [], "bare": []})
            if any(_named(h) for h in held):
                rec["guarded"].append((mk, line, qn))
            elif f.name not in _INIT_METHODS:
                rec["bare"].append((mk, line, qn, f.name))
    for (cname, attr), rec in sorted(writes.items()):
        if not rec["guarded"] or not rec["bare"]:
            continue
        gmk, gline, _ = rec["guarded"][0]
        for mk, line, qn, meth in rec["bare"]:
            emit("CC404", mk, line,
                 f"'self.{attr}' written without a lock in "
                 f"{cname}.{meth}, but lock-guarded at {gmk}:{gline} — "
                 "the guard is advisory unless every mutation takes it",
                 attr=f"{cname}.{attr}")

    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


# -- public API ---------------------------------------------------------------

def analyze_sources(sources: Dict[str, str],
                    apply_suppressions: bool = True) -> List[Finding]:
    """Analyze a corpus given as {path: source}. Cross-module rules see
    the whole dict at once."""
    corpus = _Corpus()
    trees: Dict[str, ast.Module] = {}
    findings: List[Finding] = []
    for path, src in sources.items():
        corpus.sources[path] = src
        corpus.lines[path] = src.splitlines()
        try:
            trees[path] = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                rule="CC402", severity="error",
                message=f"syntax error: {e.msg}", file=path,
                line=e.lineno or 0))
    for path, tree in trees.items():
        _collect_module(corpus, path, tree)
    for path, tree in trees.items():
        _walk_functions(corpus, path, tree)
    findings.extend(_analyze_corpus(corpus))
    if apply_suppressions:
        supp = {p: parse_suppressions(s) for p, s in sources.items()}
        kept = []
        for f in findings:
            per_line, file_wide = supp.get(f.file, ({}, set()))
            if not is_suppressed(f, per_line, file_wide):
                kept.append(f)
        findings = kept
    return findings


def analyze_source(source: str, path: str = "<string>",
                   apply_suppressions: bool = True) -> List[Finding]:
    return analyze_sources({path: source},
                           apply_suppressions=apply_suppressions)


def analyze_paths(paths: Iterable[str],
                  root: Optional[str] = None) -> List[Finding]:
    sources: Dict[str, str] = {}
    for p in iter_py_files(paths):
        rel = os.path.relpath(p, root).replace(os.sep, "/") if root else p
        with open(p, encoding="utf-8", errors="replace") as fh:
            sources[rel] = fh.read()
    return analyze_sources(sources)


# -- witness-dump audit (CC405/CC406 offline) ---------------------------------

def audit_witness(data: dict, path: str = "<witness>") -> List[Finding]:
    """Findings from a ``dump_witness()`` JSON artifact: recorded runtime
    findings pass through; order inversions and over-budget sites are
    re-derived from the raw edges/site stats as a consistency net."""
    findings: List[Finding] = []
    recorded_pairs: Set[tuple] = set()
    recorded_406: Set[tuple] = set()
    for f in data.get("findings", ()):
        findings.append(Finding(
            rule=f.get("rule", "CC405"), message=f.get("message", ""),
            file=f.get("file", path), line=int(f.get("line", 0) or 0),
            source_line=f.get("site", ""),
            extra={"witness": path}))
        if f.get("rule") == "CC405" and f.get("locks"):
            recorded_pairs.add(tuple(sorted(f["locks"])))
        if f.get("rule") == "CC406":
            recorded_406.add((f.get("site", ""), f.get("kind", "")))

    edges = {(e["from"], e["to"]): e for e in data.get("edges", ())}
    for (a, b) in sorted(edges):
        if a >= b or (b, a) not in edges:
            continue
        pair = (a, b)
        if pair in recorded_pairs:
            continue
        e1, e2 = edges[(a, b)], edges[(b, a)]
        findings.append(Finding(
            rule="CC405",
            message=f"witnessed order inversion: '{b}' after '{a}' at "
                    f"{e1['site']} but '{a}' after '{b}' at {e2['site']}",
            file=path, line=0, source_line=e1["site"],
            extra={"locks": list(pair), "witness": path}))

    budget_s = float(data.get("budget_ms", 200.0)) / 1000.0
    for key, stats in sorted((data.get("sites") or {}).items()):
        lock, _, site = key.partition("@")
        for kind in ("hold", "wait"):
            st = stats.get(kind) or {}
            if st.get("max", 0.0) > budget_s and (
                    (site, kind) not in recorded_406):
                findings.append(Finding(
                    rule="CC406",
                    message=f"lock '{lock}' {kind} max "
                            f"{st['max'] * 1e3:.1f}ms at {site} exceeds "
                            f"the {budget_s * 1e3:.0f}ms budget",
                    file=path, line=0, source_line=site,
                    extra={"lock": lock, "kind": kind, "witness": path}))
    return findings


def audit_witness_paths(paths: Iterable[str]) -> List[Finding]:
    """Audit one or more witness dumps; directories are scanned for
    ``witness_*.json``."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.startswith("witness") and f.endswith(".json")))
        else:
            files.append(p)
    findings: List[Finding] = []
    for f in files:
        try:
            with open(f) as fh:
                data = json.load(fh)
        except (OSError, ValueError) as e:
            findings.append(Finding(
                rule="CC405", severity="error",
                message=f"unreadable witness dump: {e}", file=f, line=0))
            continue
        findings.extend(audit_witness(data, path=f))
    return findings
