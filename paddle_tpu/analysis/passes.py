"""Diagnostic passes: DF* analyses registered in the static.ir pass
registry (the reference registers diagnostic graph passes alongside the
transform passes; here ``list_passes()`` surfaces both kinds and
``apply_pass`` attaches findings instead of rewriting the jaxpr).

    prog = ir.IrProgram.trace(fn, x)
    prog = ir.apply_pass(prog, ["check_dead_code", "check_nan_prone"])
    for f in prog.findings: print(f)
"""
from __future__ import annotations

from ..static.ir import register_pass
from . import dataflow

DIAGNOSTIC_PASS_NAMES = [
    "check_shape_consistency",   # DF001
    "check_dead_code",           # DF002
    "check_unused_inputs",       # DF003
    "check_collective_order",    # DF004 (single-program: cond branches)
    "check_nan_prone",           # DF005
]

register_pass("check_shape_consistency", analysis=True)(dataflow.check_shapes)
register_pass("check_dead_code", analysis=True)(dataflow.check_dead_code)
register_pass("check_unused_inputs", analysis=True)(
    dataflow.check_unused_inputs)
register_pass("check_collective_order", analysis=True)(
    dataflow.check_collective_order)
register_pass("check_nan_prone", analysis=True)(dataflow.check_nan_prone)
