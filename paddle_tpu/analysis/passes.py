"""Diagnostic passes: DF*/SH*/MEM* analyses registered in the static.ir
pass registry (the reference registers diagnostic graph passes alongside
the transform passes; here ``list_passes()`` surfaces both kinds and
``apply_pass`` attaches findings instead of rewriting the jaxpr).

    prog = ir.IrProgram.trace(fn, x)
    prog = ir.apply_pass(prog, ["check_dead_code", "check_nan_prone"])
    for f in prog.findings: print(f)

Every registered analysis pass also feeds the observability metrics
registry: each finding increments ``analysis.findings{rule=...}`` so
``telemetry_dump`` shows what static analysis flagged, not just what the
caller chose to print.
"""
from __future__ import annotations

import functools
import os

from ..static.ir import register_pass
from . import dataflow
from . import memory as memory_mod
from . import sharding as sharding_mod

DIAGNOSTIC_PASS_NAMES = [
    "check_shape_consistency",   # DF001
    "check_dead_code",           # DF002
    "check_unused_inputs",       # DF003
    "check_collective_order",    # DF004 (single-program: cond branches)
    "check_nan_prone",           # DF005
    "check_shard_safety",        # SH201/SH202 (needs a default mesh)
    "check_hbm_footprint",       # MEM301/MEM302
]


def record_findings(findings, source: str = "") -> None:
    """Count findings into the observability registry (satellite of the
    DF/SH/MEM gates: telemetry shows rule hit-rates across a run)."""
    if not findings:
        return
    try:
        from ..observability import get_registry
    except Exception:  # partial-import contexts (standalone tooling)
        return
    fam = get_registry().counter(
        "analysis.findings",
        "findings emitted by static-analysis passes, by rule",
        labelnames=("rule",))
    for f in findings:
        fam.labels(rule=f.rule).inc()


def _diagnostic(name):
    """Register ``fn(closed) -> findings`` as a read-only pass that also
    reports its findings to the metrics registry."""
    def deco(fn):
        @functools.wraps(fn)
        def run(program):
            findings = fn(program)
            record_findings(findings, source=name)
            return findings
        register_pass(name, analysis=True)(run)
        return fn
    return deco


_diagnostic("check_shape_consistency")(dataflow.check_shapes)
_diagnostic("check_dead_code")(dataflow.check_dead_code)
_diagnostic("check_unused_inputs")(dataflow.check_unused_inputs)
_diagnostic("check_collective_order")(dataflow.check_collective_order)
_diagnostic("check_nan_prone")(dataflow.check_nan_prone)


@_diagnostic("check_shard_safety")
def check_shard_safety(program):
    """SH201/SH202 over the default mesh (no mesh declared -> nothing to
    check); inputs are assumed replicated unless the program carries
    explicit specs — the conservative read of an un-annotated trace."""
    try:
        from ..distributed.auto_parallel import get_default_mesh
        mesh = get_default_mesh()
    except Exception:
        mesh = None
    if mesh is None:
        return []
    return sharding_mod.check_sharding(program, mesh)


@_diagnostic("check_hbm_footprint")
def check_hbm_footprint(program):
    """MEM301/MEM302 per jaxpr. Budget comes from ``PADDLE_HBM_GIB`` when
    set (a plain CPU trace has no chip to read it from); missed-donation
    detection needs no budget."""
    budget = os.environ.get("PADDLE_HBM_GIB")
    return memory_mod.check_hbm(
        program, budget_gib=float(budget) if budget else None)


# -- concurrency (CC4xx) ------------------------------------------------------
# Registered in the pass registry but deliberately NOT in
# DIAGNOSTIC_PASS_NAMES: the lock passes look at the repo source tree /
# process-wide witness state, not the traced program, so running them on
# every analyze() call would make unrelated program diagnostics depend on
# ambient thread activity. Invoke explicitly (or use tools/race_check.py).

@functools.lru_cache(maxsize=1)
def _repo_lock_findings():
    import os as _os
    from . import concurrency as concurrency_mod
    root = _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    roots = [p for p in (_os.path.join(root, "paddle_tpu"),
                         _os.path.join(root, "tools"))
             if _os.path.isdir(p)]
    return tuple(concurrency_mod.analyze_paths(roots, root=root))


def check_lock_discipline(program=None):
    """CC401–CC404 over the repo source tree (cached — the tree does not
    change mid-process). The ``program`` argument is accepted and ignored
    so the pass fits the registry's call shape."""
    findings = list(_repo_lock_findings())
    record_findings(findings, source="check_lock_discipline")
    return findings


def check_lock_witness(program=None):
    """CC405/CC406 accumulated by the runtime lock witness in THIS
    process (empty when ``PADDLE_LOCK_WITNESS`` is off)."""
    from ..utils.locks import witness_findings
    findings = witness_findings()
    record_findings(findings, source="check_lock_witness")
    return findings


register_pass("check_lock_discipline", analysis=True)(check_lock_discipline)
register_pass("check_lock_witness", analysis=True)(check_lock_witness)
