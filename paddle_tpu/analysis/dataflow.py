"""Jaxpr dataflow analyses (DF* rules) over ``static.ir.IrProgram``.

Each analysis is ``ClosedJaxpr -> List[Finding]`` — read-only diagnostics,
the counterpart of the transform passes in ``static/ir.py`` (the reference
ships both kinds over its IR: transform passes *and* diagnostic passes).
``analysis/passes.py`` registers these in the same pass registry so
``list_passes()`` surfaces them and ``apply_pass`` runs them without
touching the program.

Rules:
* DF001 shape/dtype consistency — def-before-use / double-def scan plus
  jax's own ``check_jaxpr`` re-check (catches corrupt hand-written passes)
* DF002 dead code — eqn results that never reach the outputs
* DF003 unused inputs — invars nothing reads
* DF004 collective ordering — every rank must see the identical collective
  sequence per mesh axis (cross-rank compare + cond-branch divergence)
* DF005 NaN-prone patterns — log/sqrt/rsqrt/div fed by unclamped subs
* DF006 inplace/donation alias audit — ops/inplace.py contract vs the
  alias metadata declared in ops/registry.py
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

try:
    from jax._src.core import (ClosedJaxpr, DropVar, Jaxpr, Literal, Var,
                               check_jaxpr)
except ImportError:  # pragma: no cover - older/newer jax layouts
    from jax.core import (ClosedJaxpr, DropVar, Jaxpr, Literal,  # type: ignore
                          Var)
    try:
        from jax.core import check_jaxpr  # type: ignore
    except ImportError:
        check_jaxpr = None  # type: ignore

from .findings import Finding

__all__ = ["check_shapes", "check_dead_code", "check_unused_inputs",
           "collective_schedule", "check_collective_order",
           "check_nan_prone", "audit_inplace_aliases", "run_all"]


def _closed(program) -> ClosedJaxpr:
    """Accept an IrProgram or a bare ClosedJaxpr."""
    return getattr(program, "closed", program)


def _prim(eqn) -> str:
    return str(eqn.primitive)


# ---------------------------------------------------------------------------
# DF001 — structural + type consistency
# ---------------------------------------------------------------------------

def check_shapes(program) -> List[Finding]:
    closed = _closed(program)
    jaxpr = closed.jaxpr
    findings: List[Finding] = []
    defined = set(jaxpr.constvars) | set(jaxpr.invars)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, Var) and v not in defined:
                findings.append(Finding(
                    "DF001",
                    f"eqn #{i} ({_prim(eqn)}) reads {v} before it is "
                    "defined — a transform pass dropped its producer",
                    line=i))
        for o in eqn.outvars:
            if isinstance(o, DropVar):
                continue
            if o in defined:
                findings.append(Finding(
                    "DF001",
                    f"eqn #{i} ({_prim(eqn)}) redefines {o} — SSA "
                    "violated", line=i))
            defined.add(o)
    for v in jaxpr.outvars:
        if isinstance(v, Var) and not isinstance(v, DropVar) \
                and v not in defined:
            findings.append(Finding(
                "DF001", f"program output {v} is never defined", line=0))
    if not findings and check_jaxpr is not None:
        try:
            check_jaxpr(jaxpr)
        except Exception as e:  # JaxprTypeError and friends
            findings.append(Finding(
                "DF001", f"jax type re-check failed: {e}", line=0))
    return findings


# ---------------------------------------------------------------------------
# DF002 / DF003 — liveness
# ---------------------------------------------------------------------------

def _live_vars(jaxpr: Jaxpr) -> set:
    """Vars that (transitively) feed outputs or effectful eqns."""
    live = {v for v in jaxpr.outvars if isinstance(v, Var)}
    for eqn in reversed(jaxpr.eqns):
        if eqn.effects or any(o in live for o in eqn.outvars):
            live.update(v for v in eqn.invars if isinstance(v, Var))
    return live


def check_dead_code(program) -> List[Finding]:
    closed = _closed(program)
    jaxpr = closed.jaxpr
    live = _live_vars(jaxpr)
    findings = []
    for i, eqn in enumerate(jaxpr.eqns):
        if not eqn.effects and not any(o in live for o in eqn.outvars):
            findings.append(Finding(
                "DF002",
                f"eqn #{i} ({_prim(eqn)}) result never reaches the "
                "outputs; the dead_code_elimination pass would remove it",
                line=i))
    return findings


def check_unused_inputs(program) -> List[Finding]:
    closed = _closed(program)
    jaxpr = closed.jaxpr
    read = {v for v in jaxpr.outvars if isinstance(v, Var)}
    for eqn in jaxpr.eqns:
        read.update(v for v in eqn.invars if isinstance(v, Var))
    findings = []
    for i, v in enumerate(jaxpr.invars):
        if v not in read:
            findings.append(Finding(
                "DF003",
                f"input #{i} ({v.aval.str_short()}) is never read — "
                "it still costs host→device transfer and a donation slot",
                line=i))
    return findings


# ---------------------------------------------------------------------------
# DF004 — collective ordering
# ---------------------------------------------------------------------------

def _collective_prims() -> frozenset:
    try:
        from ..distributed.collective import COLLECTIVE_PRIMITIVES
        return COLLECTIVE_PRIMITIVES
    except Exception:  # standalone / partial-import contexts
        return frozenset({
            "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
            "all_to_all", "psum_scatter", "reduce_scatter", "pbroadcast"})


def _axes_of(params: dict) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if axes is None:
        axes = ()
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collective_schedule(program, _path: str = "") -> List[Tuple]:
    """Ordered list of ``(path, primitive, axes)`` for every collective
    eqn, recursing into call/control-flow subjaxprs (pjit/scan/while/cond
    — cond branches get distinct paths so divergence is visible)."""
    closed = _closed(program)
    jaxpr = closed.jaxpr if isinstance(closed, ClosedJaxpr) else closed
    prims = _collective_prims()
    sched: List[Tuple] = []
    for i, eqn in enumerate(jaxpr.eqns):
        name = _prim(eqn)
        if name in prims:
            sched.append((_path, name, _axes_of(eqn.params)))
        for key, val in eqn.params.items():
            subs = val if isinstance(val, (tuple, list)) else (val,)
            for j, sub in enumerate(subs):
                if isinstance(sub, (ClosedJaxpr, Jaxpr)):
                    tag = f"{_path}/{name}#{i}.{key}"
                    if len(subs) > 1:
                        tag += f"[{j}]"
                    sched.extend(collective_schedule(sub, tag))
    return sched


def _branch_schedules(program):
    """-> {cond-path: [schedule-per-branch]} for every cond eqn."""
    closed = _closed(program)
    jaxpr = closed.jaxpr if isinstance(closed, ClosedJaxpr) else closed
    out: Dict[str, List[List[Tuple]]] = {}

    def walk(j: Jaxpr, path: str):
        for i, eqn in enumerate(j.eqns):
            name = _prim(eqn)
            if name == "cond":
                branches = eqn.params.get("branches", ())
                out[f"{path}/cond#{i}"] = [collective_schedule(b)
                                           for b in branches]
            for val in eqn.params.values():
                subs = val if isinstance(val, (tuple, list)) else (val,)
                for sub in subs:
                    if isinstance(sub, ClosedJaxpr):
                        walk(sub.jaxpr, f"{path}/{name}#{i}")
                    elif isinstance(sub, Jaxpr):
                        walk(sub, f"{path}/{name}#{i}")

    walk(jaxpr, "")
    return out


def check_collective_order(programs, rank_names: Optional[Sequence[str]] = None
                           ) -> List[Finding]:
    """DF004. Accepts ONE program (checks cond-branch divergence) or a
    sequence of per-rank programs (checks the cross-rank schedule — every
    mesh axis must see the identical collective sequence on all ranks)."""
    if isinstance(programs, (ClosedJaxpr, Jaxpr)) or hasattr(
            programs, "closed"):
        programs = [programs]
    programs = list(programs)
    findings: List[Finding] = []

    # cross-rank: compare (primitive, axes) sequences
    if len(programs) > 1:
        names = list(rank_names or [f"rank{i}"
                                    for i in range(len(programs))])
        scheds = [[(prim, axes) for (_p, prim, axes) in
                   collective_schedule(p)] for p in programs]
        ref = scheds[0]
        for r, sched in enumerate(scheds[1:], start=1):
            if sched == ref:
                continue
            # locate the first divergence for a pointable message
            i = 0
            while i < min(len(ref), len(sched)) and ref[i] == sched[i]:
                i += 1
            a = ref[i] if i < len(ref) else None
            b = sched[i] if i < len(sched) else None
            findings.append(Finding(
                "DF004",
                f"{names[0]} and {names[r]} disagree at collective #{i}: "
                f"{names[0]} issues {a}, {names[r]} issues {b} — mesh "
                "ranks will deadlock waiting on each other",
                line=i,
                extra={"ranks": [names[0], names[r]], "index": i}))

    # intra-program: cond branches must agree (ranks taking different
    # branches otherwise issue different collective sequences)
    for p in programs:
        for path, branch_scheds in _branch_schedules(p).items():
            flat = [[(prim, axes) for (_pp, prim, axes) in s]
                    for s in branch_scheds]
            if any(s != flat[0] for s in flat[1:]):
                findings.append(Finding(
                    "DF004",
                    f"cond at {path or '/'} carries different collective "
                    f"sequences per branch ({flat}) — ranks taking "
                    "different branches deadlock the mesh",
                    extra={"path": path}))
    return findings


# ---------------------------------------------------------------------------
# DF005 — NaN-prone patterns
# ---------------------------------------------------------------------------

_RISKY_UNARY = {"log", "log2", "log10", "sqrt", "rsqrt"}
#: producers that make a subtraction safe-ish (clamped / shifted)
_GUARD_PRIMS = {"max", "clamp", "clip", "abs", "exp", "add",
                "reduce_max", "square"}


def check_nan_prone(program) -> List[Finding]:
    closed = _closed(program)
    jaxpr = closed.jaxpr
    producer: Dict[Var, Tuple[int, object]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for o in eqn.outvars:
            if not isinstance(o, DropVar):
                producer[o] = (i, eqn)
    findings = []

    def produced_by_sub(v) -> Optional[int]:
        if not isinstance(v, Var) or v not in producer:
            return None
        idx, eqn = producer[v]
        return idx if _prim(eqn) == "sub" else None

    for i, eqn in enumerate(jaxpr.eqns):
        name = _prim(eqn)
        if name in _RISKY_UNARY:
            src = produced_by_sub(eqn.invars[0])
            if src is not None:
                findings.append(Finding(
                    "DF005",
                    f"eqn #{i} ({name}) consumes an unclamped subtraction "
                    f"(eqn #{src}); negative/zero inputs produce NaN/inf "
                    "— clamp or add an epsilon first",
                    line=i))
        elif name == "div" and len(eqn.invars) > 1:
            src = produced_by_sub(eqn.invars[1])
            if src is not None:
                findings.append(Finding(
                    "DF005",
                    f"eqn #{i} (div) divides by an unclamped subtraction "
                    f"(eqn #{src}); a zero difference produces inf/NaN",
                    line=i))
    return findings


# ---------------------------------------------------------------------------
# DF006 — inplace/donation alias audit (registry-level, not per-jaxpr)
# ---------------------------------------------------------------------------

def audit_inplace_aliases(namespace=None) -> List[Finding]:
    """Validate every op exposed as an ``op_`` inplace variant against the
    alias metadata declared in ``ops/registry.py``:

    * the registry entry must declare alias metadata (the donation
      contract is explicit, not implied by appearing in _INPLACE_NAMES);
    * declared ``preserves_shape`` / ``preserves_dtype`` must match the
      op's actual abstract behavior (probed with jax.eval_shape on
      canonical float32 operands where the op's arity allows).

    A wrong declaration is an ERROR: the compiled path donates the input
    buffer based on it, and a shape/dtype-changing op reusing the donated
    buffer corrupts memory on real hardware.
    """
    import jax
    import jax.numpy as jnp
    from ..ops import inplace as _inplace
    from ..ops.registry import OP_REGISTRY
    if namespace is None:
        from .. import ops as _ops
        namespace = vars(_ops)

    findings: List[Finding] = []
    probe = jax.ShapeDtypeStruct((2, 3), jnp.float32)

    for name in _inplace._INPLACE_NAMES:
        fn = namespace.get(name)
        if fn is None or not callable(fn):
            continue
        op_name = getattr(fn, "op_name", name)
        entry = OP_REGISTRY.get(op_name)
        if entry is None:
            continue
        alias = entry.get("alias")
        if alias is None:
            findings.append(Finding(
                "DF006",
                f"op '{op_name}' has an inplace variant '{name}_' but no "
                "alias metadata in the registry — donation contract is "
                "implicit", extra={"op": op_name}))
            continue
        raw = entry["fn"]
        out = None
        import warnings
        for args in ((probe,), (probe, probe)):
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    out = jax.eval_shape(raw, *args)
                break
            except Exception:
                continue
        if out is None:
            continue  # needs special operands; metadata presence checked
        leaves = jax.tree_util.tree_leaves(out)
        if len(leaves) != 1:
            continue
        o = leaves[0]
        actual_shape = tuple(o.shape) == tuple(probe.shape)
        actual_dtype = o.dtype == probe.dtype
        if alias.get("preserves_shape") and not actual_shape:
            findings.append(Finding(
                "DF006",
                f"op '{op_name}' declares preserves_shape but maps "
                f"{probe.shape} -> {tuple(o.shape)}; donating its input "
                "buffer would corrupt memory",
                extra={"op": op_name}))
        if alias.get("preserves_dtype") and not actual_dtype:
            findings.append(Finding(
                "DF006",
                f"op '{op_name}' declares preserves_dtype but maps "
                f"{probe.dtype} -> {o.dtype}; the inplace write-back "
                "silently changes the tensor's dtype",
                extra={"op": op_name}))
    return findings


# ---------------------------------------------------------------------------

_PER_PROGRAM = [check_shapes, check_dead_code, check_unused_inputs,
                check_collective_order, check_nan_prone]


def run_all(program) -> List[Finding]:
    """All per-program DF analyses over one IrProgram/ClosedJaxpr."""
    findings: List[Finding] = []
    for fn in _PER_PROGRAM:
        findings.extend(fn(program))
    return findings
