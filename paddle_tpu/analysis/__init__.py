"""Static-analysis subsystem: jaxpr dataflow diagnostics + trace-safety
lint + SPMD shard-safety + HBM-footprint budgeting.

Four rule families over three IRs (rule catalog in ``findings.RULES``):

* **DF rules** (``dataflow.py``) analyze traced jaxprs (``static.ir
  .IrProgram``): structural/type consistency, dead code, unused inputs,
  cross-rank collective ordering (the SPMD deadlock lint), NaN-prone
  numerics, and the inplace/donation alias audit of the op registry.
* **TS rules** (``ast_lint.py``) lint python source for jit-context
  hazards: host syncs, data-dependent control flow, jit-in-loop,
  trace-time side effects, and fresh-closure-capture recompiles.
  CLI: ``python tools/tpu_lint.py <paths>``.
* **SH rules** (``sharding.py``) propagate Shard/Replicate/Partial
  placements over a jaxpr against a declared mesh and audit the 7B
  plan's declared shardings: axis divisibility, implicit reshards,
  collective volume vs the ROOFLINE.json interconnect budget, and
  FSDP replication waste.
* **MEM rules** (``memory.py``) estimate peak per-chip HBM — a liveness
  walk with donation credits from the op registry's alias metadata per
  jaxpr, recorded-bytes scaling per PLAN_7B variant, and KV-cache
  pricing per gateway serving bucket. CLI: ``python tools/shard_check.py``.
* **CC rules** (``concurrency.py``) audit the serving fleet's lock
  discipline: a whole-repo lock-acquisition graph flags lock-order
  cycles (CC401), blocking calls under a lock (CC402), callbacks invoked
  while holding a lock (CC403), and unguarded shared-state mutation
  (CC404); the runtime witness (``utils.locks``) records observed
  acquisition order and hold times (CC405/CC406).
  CLI: ``python tools/race_check.py``.

DF/SH/MEM analyses are registered as read-only *diagnostic passes* in the
static.ir pass registry (``passes.py``) — ``apply_pass(prog,
"check_dead_code")`` returns the program with ``prog.findings`` populated
— and every pass run feeds ``analysis.findings{rule=...}`` counters into
the observability metrics registry.

Suppress accepted findings inline (``# tpu-lint: disable=TS101``) or via
the checked-in baselines (``tools/tpu_lint_baseline.json``).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .findings import (ERROR, WARNING, Finding, RULES, has_errors,
                       summarize)
from .ast_lint import lint_file, lint_paths, lint_source
from .dataflow import (audit_inplace_aliases, check_collective_order,
                       check_dead_code, check_nan_prone, check_shapes,
                       check_unused_inputs, collective_schedule, run_all)
from .sharding import (MeshSpec, ShardSpec, check_fsdp_replication,
                       check_plan_sharding, check_sharding, divisible_dim,
                       interconnect_budget, propagate_placements)
from .memory import (check_hbm, check_plan_memory, peak_hbm_estimate,
                     serving_bucket_report, variant_live_gib)
from .concurrency import (analyze_paths as check_concurrency,
                          analyze_source as check_concurrency_source,
                          audit_witness, audit_witness_paths)
from . import passes as _passes  # registers the diagnostic passes
from .passes import (DIAGNOSTIC_PASS_NAMES, check_lock_discipline,
                     check_lock_witness, record_findings)

__all__ = [
    "Finding", "RULES", "ERROR", "WARNING", "has_errors", "summarize",
    "lint_source", "lint_file", "lint_paths",
    "check_shapes", "check_dead_code", "check_unused_inputs",
    "check_collective_order", "check_nan_prone", "collective_schedule",
    "audit_inplace_aliases", "run_all", "analyze",
    "MeshSpec", "ShardSpec", "divisible_dim", "propagate_placements",
    "check_sharding", "check_fsdp_replication", "check_plan_sharding",
    "interconnect_budget",
    "peak_hbm_estimate", "check_hbm", "variant_live_gib",
    "check_plan_memory", "serving_bucket_report",
    "check_concurrency", "check_concurrency_source",
    "audit_witness", "audit_witness_paths",
    "check_lock_discipline", "check_lock_witness",
    "DIAGNOSTIC_PASS_NAMES", "record_findings",
]


def analyze(program, passes: Optional[Sequence[str]] = None
            ) -> List[Finding]:
    """Run the diagnostic passes (all by default) over an IrProgram or
    ClosedJaxpr and return the findings."""
    from ..static import ir
    names = list(passes) if passes is not None else DIAGNOSTIC_PASS_NAMES
    if hasattr(program, "closed"):
        return ir.apply_pass(program, names).findings
    findings: List[Finding] = []
    for n in names:
        findings.extend(ir.PASS_REGISTRY[n](program))
    return findings
