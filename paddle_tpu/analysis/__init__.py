"""Static-analysis subsystem: jaxpr dataflow diagnostics + trace-safety lint.

Two engines over two IRs (rule catalog in ``findings.RULES``):

* **DF rules** (``dataflow.py``) analyze traced jaxprs (``static.ir
  .IrProgram``): structural/type consistency, dead code, unused inputs,
  cross-rank collective ordering (the SPMD deadlock lint), NaN-prone
  numerics, and the inplace/donation alias audit of the op registry.
  Registered as read-only *diagnostic passes* in the static.ir pass
  registry (``passes.py``) — ``apply_pass(prog, "check_dead_code")``
  returns the program with ``prog.findings`` populated.
* **TS rules** (``ast_lint.py``) lint python source for jit-context
  hazards: host syncs, data-dependent control flow, jit-in-loop, and
  trace-time side effects. CLI: ``python tools/tpu_lint.py <paths>``
  (runs under tier-1 via the ``lint`` pytest marker).

Suppress accepted findings inline (``# tpu-lint: disable=TS101``) or via
the checked-in baseline (``tools/tpu_lint_baseline.json``).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .findings import (ERROR, WARNING, Finding, RULES, has_errors,
                       summarize)
from .ast_lint import lint_file, lint_paths, lint_source
from .dataflow import (audit_inplace_aliases, check_collective_order,
                       check_dead_code, check_nan_prone, check_shapes,
                       check_unused_inputs, collective_schedule, run_all)
from . import passes as _passes  # registers the diagnostic passes
from .passes import DIAGNOSTIC_PASS_NAMES

__all__ = [
    "Finding", "RULES", "ERROR", "WARNING", "has_errors", "summarize",
    "lint_source", "lint_file", "lint_paths",
    "check_shapes", "check_dead_code", "check_unused_inputs",
    "check_collective_order", "check_nan_prone", "collective_schedule",
    "audit_inplace_aliases", "run_all", "analyze",
    "DIAGNOSTIC_PASS_NAMES",
]


def analyze(program, passes: Optional[Sequence[str]] = None
            ) -> List[Finding]:
    """Run the diagnostic passes (all by default) over an IrProgram or
    ClosedJaxpr and return the findings."""
    from ..static import ir
    names = list(passes) if passes is not None else DIAGNOSTIC_PASS_NAMES
    if hasattr(program, "closed"):
        return ir.apply_pass(program, names).findings
    findings: List[Finding] = []
    for n in names:
        findings.extend(ir.PASS_REGISTRY[n](program))
    return findings
