"""SPMD shard-safety analysis (SH2xx rules).

GSPMD-style systems validate sharding propagation *before* compiling for
the mesh; this module does the static half of that for paddle_tpu so a
``PLAN_7B.json`` variant (or any traced program) is proven shard-feasible
on the CPU-only fallback path before a chip ever runs it.

Two entry layers:

* **plan-level** (stdlib-only, no jax): ``check_plan_sharding`` audits the
  7B plan's declared parameter shardings against a mesh — axis
  divisibility (SH201), FSDP replication waste (SH204) and the analytic
  per-step collective volume vs the interconnect budget derived from
  ``ROOFLINE.json`` (SH203). ``tools/shard_check.py`` imports this module
  straight off the tree (no package, no jax), same as ``tpu_lint`` does
  with ``ast_lint``.
* **jaxpr-level** (lazy jax import): ``propagate_placements`` pushes
  ``Shard``/``Replicate``/``Partial`` placements through a jaxpr's
  equations — contraction over a matched sharded dim yields ``Partial``
  (pending psum), mismatched operand placements flag SH202 (XLA would
  insert an implicit all-gather/reshard on the hot path), collective
  primitives are costed against the mesh so ``check_sharding`` can apply
  the SH203 budget.

Rules:
* SH201 (error)   shard-axis-divisibility — a dim declared ``Shard(axis)``
  must divide by the mesh axis degree; the runtime placement policy
  (``distributed/sharding.py``) replicates instead, so a plan assuming
  the shard is simply wrong.
* SH202 (warning) sharding-mismatch at an equation.
* SH203 (warning) estimated collective bytes over the interconnect budget.
* SH204 (warning) replicated-parameter-under-FSDP.
"""
from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

try:
    from .findings import ERROR, Finding, WARNING
except ImportError:  # loaded standalone by tools/shard_check.py
    from findings import ERROR, Finding, WARNING  # type: ignore

__all__ = [
    "MeshSpec", "ShardSpec", "PropagationResult", "divisible_dim",
    "dtype_bytes", "nbytes", "check_spec_divisibility",
    "propagate_placements", "check_sharding", "check_fsdp_replication",
    "ici_bytes_per_s", "interconnect_budget", "LLAMA7B_DIMS",
    "plan_param_shapes", "plan_shard_dim", "plan_mesh_size",
    "plan_step_collective_bytes", "plan_step_flops_per_chip",
    "check_plan_sharding",
]

GIB = 1024 ** 3

#: v5e chip: HBM ~819 GB/s vs a single ICI link ~200 GB/s; when
#: ROOFLINE.json carries no explicit ``peak_ici`` we derive it from the
#: recorded HBM roof with this ratio.
ICI_HBM_RATIO = 4.0


def divisible_dim(shape: Sequence[int], degree: int) -> Optional[int]:
    """First dim the axis degree divides (dim0 preferred), else None.

    Single source of truth for the placement policy — the runtime
    (``distributed/sharding.py``) and the static SH201/SH204 checks must
    agree on which dim a parameter shards over.
    """
    for d, size in enumerate(shape):
        if size % degree == 0 and size >= degree:
            return d
    return None


_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "f32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
    "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "float8_e4m3fn": 1,
    "float8_e5m2": 1,
}


def dtype_bytes(dtype) -> int:
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize:
        return int(itemsize)
    return _DTYPE_BYTES.get(str(dtype), 4)


def nbytes(shape: Sequence[int], dtype="float32") -> int:
    return math.prod(shape) * dtype_bytes(dtype) if shape is not None else 0


class MeshSpec:
    """Named mesh axes with degrees; the static mirror of ProcessMesh."""

    __slots__ = ("axes",)

    def __init__(self, axes):
        if not isinstance(axes, dict):
            axes = dict(axes)
        self.axes: Dict[str, int] = {str(k): int(v) for k, v in axes.items()}

    @classmethod
    def from_any(cls, mesh) -> "MeshSpec":
        if isinstance(mesh, MeshSpec):
            return mesh
        if isinstance(mesh, dict):
            return cls(mesh)
        if hasattr(mesh, "dim_names") and hasattr(mesh, "get_dim_size"):
            return cls({n: mesh.get_dim_size(n) for n in mesh.dim_names})
        if hasattr(mesh, "axis_names") and hasattr(mesh, "shape"):
            return cls({n: mesh.shape[n] for n in mesh.axis_names})
        raise TypeError(f"cannot interpret {mesh!r} as a mesh")

    def degree(self, axes) -> int:
        """Product of the degrees of the given axis names (unknown: 1)."""
        if isinstance(axes, str):
            axes = (axes,)
        deg = 1
        for a in axes:
            deg *= self.axes.get(str(a), 1)
        return deg

    @property
    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def __repr__(self):
        body = ",".join(f"{k}={v}" for k, v in self.axes.items())
        return f"MeshSpec({body})"


class ShardSpec:
    """Per-tensor placement: a tuple of mesh-axis tuples per dim, plus a
    ``partial`` set of axes over which the values are pending a psum."""

    __slots__ = ("dims", "partial")

    def __init__(self, dims, partial=()):
        norm = []
        for d in dims:
            if d is None:
                norm.append(())
            elif isinstance(d, str):
                norm.append((d,))
            else:
                norm.append(tuple(d))
        self.dims: Tuple[Tuple[str, ...], ...] = tuple(norm)
        self.partial = frozenset(partial)

    @classmethod
    def replicated(cls, ndim: int) -> "ShardSpec":
        return cls(((),) * ndim)

    @classmethod
    def normalize(cls, spec, ndim: int) -> "ShardSpec":
        if spec is None:
            return cls.replicated(ndim)
        if isinstance(spec, ShardSpec):
            return spec
        return cls(tuple(spec))

    @property
    def is_replicated(self) -> bool:
        return not any(self.dims) and not self.partial

    def shard_fraction(self, mesh: MeshSpec) -> float:
        """1/N of the global bytes held per chip under this placement."""
        deg = 1
        for axes in self.dims:
            deg *= mesh.degree(axes)
        return 1.0 / deg

    def with_partial(self, axes) -> "ShardSpec":
        return ShardSpec(self.dims, self.partial | frozenset(axes))

    def __eq__(self, other):
        return (isinstance(other, ShardSpec) and self.dims == other.dims
                and self.partial == other.partial)

    def __hash__(self):
        return hash((self.dims, self.partial))

    def __repr__(self):
        body = ",".join("+".join(a) if a else "·" for a in self.dims)
        tail = f"|partial={sorted(self.partial)}" if self.partial else ""
        return f"ShardSpec[{body}{tail}]"


# ---------------------------------------------------------------------------
# SH201 — axis divisibility (works on bare shapes; no jax)
# ---------------------------------------------------------------------------

def check_spec_divisibility(name: str, shape: Sequence[int], spec,
                            mesh, file: str = "<plan>",
                            line: int = 0) -> List[Finding]:
    mesh = MeshSpec.from_any(mesh)
    spec = ShardSpec.normalize(spec, len(shape))
    findings = []
    for d, axes in enumerate(spec.dims):
        deg = mesh.degree(axes)
        if deg > 1 and shape[d] % deg:
            findings.append(Finding(
                "SH201",
                f"'{name}' dim {d} (size {shape[d]}) is declared "
                f"Shard({'+'.join(axes)}) but {shape[d]} % {deg} != 0 — "
                "the placement policy would replicate it and the plan's "
                "per-chip math is wrong",
                file=file, line=line, severity=ERROR,
                extra={"param": name, "dim": d, "degree": deg}))
    return findings


# ---------------------------------------------------------------------------
# SH204 — replicated parameter under an FSDP axis (no jax)
# ---------------------------------------------------------------------------

def check_fsdp_replication(params: Dict[str, tuple], mesh, axis: str,
                           min_bytes: int = 1 << 20, dtype="bfloat16",
                           file: str = "<plan>") -> List[Finding]:
    """``params``: name -> (shape, spec-or-None). A param left fully
    replicated over the FSDP axis although a divisible dim exists wastes
    (N-1)/N of its per-chip bytes on every chip."""
    mesh = MeshSpec.from_any(mesh)
    n = mesh.degree(axis)
    findings = []
    if n <= 1:
        return findings
    for name, (shape, spec) in params.items():
        spec = ShardSpec.normalize(spec, len(shape))
        if any(axis in axes for axes in spec.dims):
            continue
        size = nbytes(shape, dtype)
        if size < min_bytes:
            continue
        dim = divisible_dim(shape, n)
        if dim is None:
            continue
        waste = size * (n - 1) // n
        findings.append(Finding(
            "SH204",
            f"'{name}' ({size / GIB:.3f} GiB) stays replicated over FSDP "
            f"axis '{axis}' (degree {n}) although dim {dim} is divisible "
            f"— {waste / GIB:.3f} GiB/chip is redundant",
            file=file, severity=WARNING,
            extra={"param": name, "dim": dim, "waste_bytes": waste}))
    return findings


# ---------------------------------------------------------------------------
# Interconnect budget (ROOFLINE.json; no jax)
# ---------------------------------------------------------------------------

def ici_bytes_per_s(roofline: dict) -> float:
    ici = roofline.get("peak_ici")
    if ici:
        return float(ici)
    return float(roofline.get("peak_hbm", 8.19e11)) / ICI_HBM_RATIO


def interconnect_budget(roofline: dict, step_flops: float,
                        overlap_frac: float = 1.0) -> float:
    """Collective bytes the interconnect can move while the chip computes
    ``step_flops`` at the roofline's peak — beyond this the step is
    ICI-bound (SH203)."""
    t_compute = step_flops / float(roofline["peak_flops"])
    return ici_bytes_per_s(roofline) * t_compute * overlap_frac


# ---------------------------------------------------------------------------
# Jaxpr-level placement propagation (lazy jax import)
# ---------------------------------------------------------------------------

class PropagationResult:
    __slots__ = ("var_specs", "findings", "collective_bytes",
                 "reshard_bytes")

    def __init__(self):
        self.var_specs: Dict = {}
        self.findings: List[Finding] = []
        self.collective_bytes = 0.0   # explicit collectives (psum, ...)
        self.reshard_bytes = 0.0      # implicit gathers from SH202 sites

    @property
    def total_bytes(self) -> float:
        return self.collective_bytes + self.reshard_bytes


def _jax_core():
    try:
        from jax._src.core import ClosedJaxpr, DropVar, Jaxpr, Literal, Var
    except ImportError:  # pragma: no cover - older/newer jax layouts
        from jax.core import (ClosedJaxpr, DropVar, Jaxpr,  # type: ignore
                              Literal, Var)
    return ClosedJaxpr, DropVar, Jaxpr, Literal, Var


_ELEMENTWISE_SAFE_PARTIAL = {"add", "sub", "neg", "psum", "convert_element_type",
                             "copy", "transpose", "reshape", "broadcast_in_dim"}


def _gather_cost(aval, spec: ShardSpec, mesh: MeshSpec) -> float:
    """Bytes moved to materialize the replicated form of a sharded value."""
    total = nbytes(tuple(aval.shape), aval.dtype)
    return total * (1.0 - spec.shard_fraction(mesh))


def propagate_placements(program, mesh, in_specs=None) -> PropagationResult:
    """Push placements through a jaxpr. ``in_specs``: one spec per invar
    (None entries = replicated); sizes are read from the avals as-traced
    (global view). Emits SH202 findings at mismatch sites and tallies
    explicit-collective + implicit-reshard bytes for the SH203 budget."""
    ClosedJaxpr, DropVar, Jaxpr, Literal, Var = _jax_core()
    closed = getattr(program, "closed", program)
    jaxpr = closed.jaxpr if isinstance(closed, ClosedJaxpr) else closed
    mesh = MeshSpec.from_any(mesh)
    res = PropagationResult()

    if in_specs is None:
        in_specs = [None] * len(jaxpr.invars)
    env: Dict = {}
    for i, (v, spec) in enumerate(zip(jaxpr.invars, in_specs)):
        ndim = len(getattr(v.aval, "shape", ()))
        s = ShardSpec.normalize(spec, ndim)
        env[v] = s
        res.findings.extend(check_spec_divisibility(
            f"input #{i}", tuple(v.aval.shape), s, mesh, file="<jaxpr>"))
    for v in jaxpr.constvars:
        env[v] = ShardSpec.replicated(len(getattr(v.aval, "shape", ())))

    def spec_of(atom) -> ShardSpec:
        if isinstance(atom, Literal):
            return ShardSpec.replicated(len(getattr(atom.aval, "shape", ())))
        return env.get(atom,
                       ShardSpec.replicated(len(getattr(atom.aval, "shape",
                                                        ()))))

    def mismatch(idx, prim, detail, moved_bytes):
        res.reshard_bytes += moved_bytes
        res.findings.append(Finding(
            "SH202",
            f"eqn #{idx} ({prim}): {detail} — XLA inserts an implicit "
            f"all-gather/reshard (~{moved_bytes / (1 << 20):.1f} MiB) on "
            "the hot path",
            line=idx, severity=WARNING,
            extra={"eqn": idx, "primitive": prim}))

    collective_prims = _collective_prims()

    for idx, eqn in enumerate(jaxpr.eqns):
        prim = str(eqn.primitive)
        specs = [spec_of(a) for a in eqn.invars]
        outs = _infer_eqn(idx, eqn, prim, specs, mesh, res, mismatch,
                          collective_prims, ClosedJaxpr, Jaxpr)
        for o, s in zip(eqn.outvars, outs):
            if not isinstance(o, DropVar):
                env[o] = s

    res.var_specs = env
    return res


def _collective_prims() -> frozenset:
    try:
        from .dataflow import _collective_prims as dfprims
        return dfprims()
    except Exception:  # pragma: no cover - standalone context
        return frozenset({
            "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
            "all_to_all", "psum_scatter", "reduce_scatter", "pbroadcast"})


def _axis_names(params: dict) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if axes is None:
        axes = ()
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _infer_eqn(idx, eqn, prim, specs, mesh, res, mismatch,
               collective_prims, ClosedJaxpr, Jaxpr):
    """-> one ShardSpec per outvar; side effects: findings + byte tallies."""
    out_ndims = [len(getattr(o.aval, "shape", ())) for o in eqn.outvars]

    # -- explicit collectives: cost them, resolve Partial on psum --------
    if prim in collective_prims:
        axes = _axis_names(eqn.params)
        n = mesh.degree(axes)
        in_spec = specs[0] if specs else ShardSpec.replicated(0)
        size = nbytes(tuple(eqn.invars[0].aval.shape),
                      eqn.invars[0].aval.dtype) if eqn.invars else 0
        if n > 1:
            if prim in ("psum", "pmax", "pmin"):
                # a psum resolving a Partial is one reduce; a plain
                # all-reduce costs ~2(n-1)/n of the payload
                factor = ((n - 1) / n if set(axes) <= in_spec.partial
                          else 2.0 * (n - 1) / n)
                res.collective_bytes += size * factor * max(
                    in_spec.shard_fraction(mesh), 1.0 / mesh.size)
            elif prim == "all_gather":
                out_size = nbytes(tuple(eqn.outvars[0].aval.shape),
                                  eqn.outvars[0].aval.dtype)
                res.collective_bytes += out_size * (n - 1) / n
            elif prim in ("psum_scatter", "reduce_scatter"):
                res.collective_bytes += size * (n - 1) / n
            else:  # ppermute / all_to_all / broadcasts: payload once
                res.collective_bytes += size
        outs = []
        for s, nd in zip(specs, out_ndims):
            cleared = s.partial - set(axes) if prim == "psum" else s.partial
            outs.append(ShardSpec(s.dims[:nd] if len(s.dims) >= nd
                                  else ((),) * nd, cleared))
        while len(outs) < len(out_ndims):
            outs.append(ShardSpec.replicated(out_ndims[len(outs)]))
        return outs

    # -- dot_general: contraction semantics ------------------------------
    if prim == "dot_general":
        return [_infer_dot(idx, eqn, specs, mesh, mismatch)]

    # -- structural prims -------------------------------------------------
    if prim == "transpose":
        perm = eqn.params.get("permutation", ())
        s = specs[0]
        return [ShardSpec(tuple(s.dims[p] for p in perm), s.partial)]

    if prim == "broadcast_in_dim":
        s = specs[0]
        bdims = eqn.params.get("broadcast_dimensions", ())
        out_shape = tuple(eqn.outvars[0].aval.shape)
        in_shape = tuple(eqn.invars[0].aval.shape)
        dims = [()] * len(out_shape)
        for j, bd in enumerate(bdims):
            if j < len(in_shape) and in_shape[j] == out_shape[bd]:
                dims[bd] = s.dims[j]
        return [ShardSpec(dims, s.partial)]

    if prim == "reshape":
        s = specs[0]
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(eqn.outvars[0].aval.shape)
        if in_shape == out_shape:
            return [s]
        keep = 0
        while (keep < min(len(in_shape), len(out_shape))
               and in_shape[keep] == out_shape[keep]):
            keep += 1
        dims = list(s.dims[:keep]) + [()] * (len(out_shape) - keep)
        return [ShardSpec(dims, s.partial)]

    # -- call / remat recursion -------------------------------------------
    for key in ("jaxpr", "call_jaxpr"):
        sub = eqn.params.get(key)
        if isinstance(sub, (ClosedJaxpr, Jaxpr)):
            subj = sub.jaxpr if isinstance(sub, ClosedJaxpr) else sub
            if len(subj.invars) == len(eqn.invars):
                sub_res = propagate_placements(sub, mesh, list(specs))
                for f in sub_res.findings:
                    if f.rule == "SH202":
                        f.extra.setdefault("path", f"{prim}#{idx}")
                        res.findings.append(f)
                res.collective_bytes += sub_res.collective_bytes
                res.reshard_bytes += sub_res.reshard_bytes
                outs = []
                for v, nd in zip(subj.outvars, out_ndims):
                    s = sub_res.var_specs.get(v)
                    outs.append(s if isinstance(s, ShardSpec)
                                else ShardSpec.replicated(nd))
                return outs
            break

    # -- elementwise / same-shape unify -----------------------------------
    if len(eqn.outvars) == 1 and eqn.invars:
        out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
        arrayish = [(a, s) for a, s in zip(eqn.invars, specs)
                    if tuple(getattr(a.aval, "shape", ())) == out_shape]
        if arrayish and all(
                tuple(getattr(a.aval, "shape", ())) in (out_shape, ())
                for a in eqn.invars):
            dims = []
            for d in range(len(out_shape)):
                cands = []
                for _a, s in arrayish:
                    if d < len(s.dims) and s.dims[d] and \
                            s.dims[d] not in cands:
                        cands.append(s.dims[d])
                if len(cands) > 1:
                    loser_a, loser_s = arrayish[-1]
                    mismatch(idx, prim,
                             f"operands disagree on dim {d} placement "
                             f"({cands[0]} vs {cands[1]})",
                             _gather_cost(loser_a.aval, loser_s, mesh))
                dims.append(cands[0] if cands else ())
            partial = frozenset().union(*(s.partial for _a, s in arrayish))
            return [ShardSpec(dims, partial)]

    # -- conservative fallback -------------------------------------------
    if (len(eqn.outvars) == 1 and len(eqn.invars) >= 1
            and tuple(getattr(eqn.invars[0].aval, "shape", ()))
            == tuple(getattr(eqn.outvars[0].aval, "shape", ()))):
        return [specs[0]]
    return [ShardSpec.replicated(nd) for nd in out_ndims]


def _infer_dot(idx, eqn, specs, mesh, mismatch) -> ShardSpec:
    ls, rs = specs[0], specs[1]
    lhs, rhs = eqn.invars[0], eqn.invars[1]
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    partial = set(ls.partial | rs.partial)
    out_dims: List[Tuple[str, ...]] = []

    for dl, dr in zip(lb, rb):
        al, ar = ls.dims[dl], rs.dims[dr]
        if al != ar and (al or ar):
            mismatch(idx, "dot_general",
                     f"batch dim sharded differently (lhs {al or '·'} vs "
                     f"rhs {ar or '·'})",
                     _gather_cost(rhs.aval, rs, mesh))
        out_dims.append(al or ar)

    for dl, dr in zip(lc, rc):
        al, ar = ls.dims[dl], rs.dims[dr]
        if al and al == ar:
            partial |= set(al)          # matched shard: psum pending
        elif al or ar:
            moved = 0.0
            if al:
                moved += _gather_cost(lhs.aval, ls, mesh)
            if ar:
                moved += _gather_cost(rhs.aval, rs, mesh)
            mismatch(idx, "dot_general",
                     f"contraction dim sharded on one side only "
                     f"(lhs {al or '·'} vs rhs {ar or '·'})", moved)

    lhs_free = [d for d in range(len(ls.dims)) if d not in lc and d not in lb]
    rhs_free = [d for d in range(len(rs.dims)) if d not in rc and d not in rb]
    out_dims += [ls.dims[d] for d in lhs_free] + [rs.dims[d]
                                                  for d in rhs_free]
    return ShardSpec(out_dims, partial)


def check_sharding(program, mesh, in_specs=None,
                   collective_budget_bytes: Optional[float] = None,
                   roofline: Optional[dict] = None,
                   step_flops: Optional[float] = None) -> List[Finding]:
    """SH201/SH202 via propagation, plus SH203 when a budget is known —
    either an explicit byte budget or ``roofline + step_flops``."""
    res = propagate_placements(program, mesh, in_specs)
    findings = list(res.findings)
    budget = collective_budget_bytes
    if budget is None and roofline is not None and step_flops:
        budget = interconnect_budget(roofline, step_flops)
    if budget is not None and res.total_bytes > budget:
        findings.append(Finding(
            "SH203",
            f"estimated collective traffic {res.total_bytes / GIB:.2f} GiB "
            f"exceeds the interconnect budget {budget / GIB:.2f} GiB — "
            "the step is ICI-bound, not compute-bound",
            severity=WARNING,
            extra={"collective_bytes": res.collective_bytes,
                   "reshard_bytes": res.reshard_bytes,
                   "budget_bytes": budget}))
    return findings


# ---------------------------------------------------------------------------
# Plan-level audit (stdlib-only; mirrors tools/plan_7b.py)
# ---------------------------------------------------------------------------

#: LLaMA-7B dims, kept in lockstep with tools/plan_7b.py:_llama7b_dims.
LLAMA7B_DIMS = dict(L=32, H=4096, I=11008, V=32000, heads=32, kv_heads=32)


def plan_param_shapes(dims: Optional[dict] = None) -> Dict[str, tuple]:
    """Parameter shapes of the 7B plan (mirror of plan_7b._param_shapes)."""
    d = dict(LLAMA7B_DIMS, **(dims or {}))
    L, H, I, V = d["L"], d["H"], d["I"], d["V"]
    return {
        "embed": (V, H),
        "wq": (L, H, H), "wk": (L, H, H), "wv": (L, H, H), "wo": (L, H, H),
        "w_gate": (L, H, I), "w_up": (L, H, I), "w_down": (L, I, H),
        "ln1": (L, H), "ln2": (L, H), "ln_f": (H,),
        "lm_head": (H, V),
    }


def plan_shard_dim(name: str, shape: Sequence[int]) -> Optional[int]:
    """The dim the plan declares Shard('z') on (plan_7b._shardings):
    norms replicate, 2D shards dim0, 3D shards dim1 (the per-layer
    leading dim stays whole)."""
    if name.startswith("ln") or len(shape) < 2:
        return None
    return 0 if len(shape) == 2 else 1


def plan_mesh_size(plan: dict, default: int = 16) -> int:
    topo = str(plan.get("topology", ""))
    m = re.search(r"(\d+)\s*-\s*chip", topo)
    return int(m.group(1)) if m else default


#: FLOPs multiplier per remat policy: full recomputes the forward in the
#: backward (4/3 of the base 6·P·tokens), selective recomputes roughly
#: half of it.
REMAT_FLOPS_MULT = {"full": 4.0 / 3.0, "selective": 7.0 / 6.0}


def plan_step_collective_bytes(n_params: int, n_chips: int,
                               stage: str) -> float:
    """Analytic per-chip collective bytes of one ZeRO train step:
    bf16 param all-gather (twice under stage-3: forward + backward
    re-gather) plus the f32 grad reduce-scatter."""
    frac = (n_chips - 1) / n_chips
    ag_params = 2.0 * n_params * frac          # bf16 all-gather
    rs_grads = 4.0 * n_params * frac           # f32 reduce-scatter
    if stage in ("s3", "p_g_os"):
        return 2.0 * ag_params + rs_grads
    return ag_params + rs_grads


def plan_step_flops_per_chip(n_params: int, tokens_per_chip: float,
                             remat: str = "selective") -> float:
    mult = REMAT_FLOPS_MULT.get(remat, 1.0)
    return 6.0 * n_params * tokens_per_chip * mult


def check_plan_sharding(plan: dict, mesh_size: Optional[int] = None,
                        roofline: Optional[dict] = None,
                        dims: Optional[dict] = None,
                        overlap_frac: float = 1.0,
                        file: str = "<plan>") -> List[Finding]:
    """SH201/SH203/SH204 over every training variant of a PLAN_7B dict."""
    findings: List[Finding] = []
    n = mesh_size or plan_mesh_size(plan)
    mesh = MeshSpec({"z": n})
    shapes = plan_param_shapes(dims)

    # SH201: the declared shard dim of every (master-)sharded param must
    # divide; SH204: params with NO divisible dim fall back to replication
    # under the FSDP axis.
    fsdp_tree: Dict[str, tuple] = {}
    for name, shape in shapes.items():
        dim = plan_shard_dim(name, shape)
        if dim is None:
            continue
        spec = [None] * len(shape)
        spec[dim] = "z"
        findings.extend(check_spec_divisibility(
            name, shape, spec, mesh, file=file))
        fallback = divisible_dim(shape, n)
        fsdp_tree[name] = (shape, None if fallback is None else spec)
    findings.extend(check_fsdp_replication(
        fsdp_tree, mesh, "z", file=file))

    # SH203: analytic collective volume vs the roofline-derived budget.
    if roofline is not None:
        for var in plan.get("variants", ()):
            vname = var.get("variant", "?")
            stage = "s3" if vname.startswith("s3") or vname == "p_g_os" \
                else "s2"
            n_params = var.get("n_params") or sum(
                math.prod(s) for s in shapes.values())
            batch = var.get("batch", 16)
            seq = var.get("seq", 2048)
            tokens_per_chip = batch * seq / n
            coll = plan_step_collective_bytes(n_params, n, stage)
            flops = plan_step_flops_per_chip(
                n_params, tokens_per_chip, var.get("remat", "selective"))
            budget = interconnect_budget(roofline, flops, overlap_frac)
            if coll > budget:
                t_ici = coll / ici_bytes_per_s(roofline)
                t_cmp = flops / float(roofline["peak_flops"])
                findings.append(Finding(
                    "SH203",
                    f"variant '{var.get('name', vname)}': "
                    f"{coll / GIB:.1f} GiB of collectives need "
                    f"{t_ici * 1e3:.0f} ms on the interconnect but the "
                    f"step only computes for {t_cmp * 1e3:.0f} ms — "
                    "ICI-bound",
                    file=file, severity=WARNING,
                    extra={"variant": var.get("name", vname),
                           "collective_bytes": coll,
                           "budget_bytes": budget}))
    return findings
