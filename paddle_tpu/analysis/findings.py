"""Finding model + rule catalog + suppression/baseline machinery.

Deliberately dependency-free (stdlib only): ``tools/tpu_lint.py`` imports
this module *without* importing ``paddle_tpu`` (and therefore without
importing jax), so the CLI lints the whole tree in a couple of seconds.
The jaxpr-side analyses (dataflow.py) import jax; they attach here only
through the shared ``Finding`` type.
"""
from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

ERROR = "error"
WARNING = "warning"

#: Stable rule catalog. IDs never change meaning once shipped; retire by
#: leaving a tombstone comment, never by reusing the number.
#: DF* rules run over traced jaxprs (analysis/dataflow.py, also exposed as
#: read-only diagnostic passes in the static.ir pass registry); TS* rules
#: run over python source (analysis/ast_lint.py + tools/tpu_lint.py);
#: SH* rules check SPMD shard-safety (analysis/sharding.py) and MEM* rules
#: check per-chip HBM budgets (analysis/memory.py) — both also run over
#: PLAN_7B.json variants via tools/shard_check.py.
RULES: Dict[str, dict] = {
    "DF001": dict(severity=ERROR, name="shape-dtype-consistency",
                  doc="jaxpr is structurally broken: a variable is used "
                      "before definition, defined twice, or fails jax's "
                      "own type re-check (typically a corrupt hand-written "
                      "transform pass)."),
    "DF002": dict(severity=WARNING, name="dead-code",
                  doc="equation results never reach the program outputs; "
                      "run the dead_code_elimination pass."),
    "DF003": dict(severity=WARNING, name="unused-input",
                  doc="a program input is never read; dead arguments "
                      "still cost transfer + donation slots."),
    "DF004": dict(severity=ERROR, name="collective-mismatch",
                  doc="ranks disagree on the collective sequence over a "
                      "mesh axis (or cond branches carry different "
                      "collectives) — the classic SPMD deadlock."),
    "DF005": dict(severity=WARNING, name="nan-prone",
                  doc="log/sqrt/rsqrt/div fed by an unclamped subtraction; "
                      "clamp or add an epsilon before the transcendental."),
    "DF006": dict(severity=ERROR, name="inplace-alias",
                  doc="an op exposed as an inplace variant has missing or "
                      "wrong alias/donation metadata in the op registry."),
    "TS101": dict(severity=ERROR, name="host-sync-in-jit",
                  doc=".item()/.numpy()/float()/np.asarray on a traced "
                      "value inside a @jit/to_static function forces a "
                      "host sync (ConcretizationTypeError or a silent "
                      "graph break)."),
    "TS102": dict(severity=WARNING, name="data-dependent-control-flow",
                  doc="python if/while on a traced value inside a jit "
                      "context; use lax.cond/where or accept the SOT "
                      "graph break knowingly."),
    "TS103": dict(severity=WARNING, name="jit-in-loop",
                  doc="jax.jit / to_static constructed inside a loop "
                      "defeats the executable cache (one compile per "
                      "iteration)."),
    "TS104": dict(severity=WARNING, name="side-effect-in-trace",
                  doc="side effect inside a traced function (print of a "
                      "traced value, mutation of outer python state) runs "
                      "at trace time only — replay will not repeat it."),
    "TS105": dict(severity=WARNING, name="fresh-capture-recompile",
                  doc="a fresh array/tensor literal built in an enclosing "
                      "function is captured by a nested @jit/to_static "
                      "closure; every rebuild hashes as a new constant and "
                      "silently recompiles — hoist it to module scope or "
                      "pass it as an argument."),
    "SH201": dict(severity=ERROR, name="shard-axis-divisibility",
                  doc="a dim declared Shard(axis) is not divisible by the "
                      "mesh axis degree; the placement policy would fall "
                      "back to replication, so the plan's per-chip math "
                      "is wrong."),
    "SH202": dict(severity=WARNING, name="sharding-mismatch",
                  doc="operands of one equation disagree on placement "
                      "(e.g. a contraction dim sharded on one side only); "
                      "XLA inserts an implicit all-gather/reshard on the "
                      "hot path."),
    "SH203": dict(severity=WARNING, name="collective-over-interconnect",
                  doc="estimated per-step collective bytes exceed the "
                      "interconnect budget derived from ROOFLINE.json — "
                      "the step is ICI-bound, not compute-bound."),
    "SH204": dict(severity=WARNING, name="replicated-param-under-fsdp",
                  doc="a parameter stays fully replicated over the FSDP "
                      "axis although a divisible dim exists: (N-1)/N of "
                      "its per-chip bytes are redundant."),
    "MEM301": dict(severity=ERROR, name="plan-over-hbm-budget",
                  doc="estimated per-chip peak HBM exceeds the declared "
                      "hbm_per_chip_gib for a variant not already "
                      "recorded infeasible — the plan would OOM on the "
                      "first step."),
    "MEM302": dict(severity=WARNING, name="missing-donation-or-remat",
                  doc="headroom exists but is not taken: a large input "
                      "dies at an alias-eligible op without being "
                      "donated, or a sibling remat/sharding variant at "
                      "the same batch fits the budget."),
    "CC401": dict(severity=ERROR, name="lock-order-cycle",
                  doc="two sites acquire the same pair of locks in "
                      "opposite order (propagated through the call "
                      "graph) — the classic ABBA deadlock; pick one "
                      "canonical order and stick to it."),
    "CC402": dict(severity=WARNING, name="blocking-call-under-lock",
                  doc="a blocking operation (device_put / thread join / "
                      "sleep / file IO / queue.get) runs while a lock is "
                      "held; every other thread contending on that lock "
                      "stalls for the full blocking latency."),
    "CC403": dict(severity=WARNING, name="lock-held-across-callback",
                  doc="a user/chaos callback is invoked with a private "
                      "lock held; the callback can re-enter the owning "
                      "object (self-deadlock) or block arbitrarily long "
                      "while holding it."),
    "CC404": dict(severity=WARNING, name="unguarded-shared-mutation",
                  doc="an attribute is written under a lock at some "
                      "sites but mutated with no lock held at another "
                      "(outside __init__) — the guard is advisory, not "
                      "a guarantee."),
    "CC405": dict(severity=ERROR, name="witnessed-order-inversion",
                  doc="the runtime lock-order witness observed the same "
                      "pair of TracedLocks acquired in both orders "
                      "(PADDLE_LOCK_WITNESS=1): a real interleaving away "
                      "from deadlock, not a static may-alias guess."),
    "CC406": dict(severity=WARNING, name="lock-hold-over-budget",
                  doc="a TracedLock was held (or waited on) longer than "
                      "the hold budget (PADDLE_LOCK_BUDGET_MS); hot-path "
                      "sections must stay microseconds — move the slow "
                      "work outside the critical section."),
}


@dataclass
class Finding:
    rule: str
    message: str
    file: str = "<jaxpr>"
    line: int = 0
    col: int = 0
    severity: str = ""          # defaulted from RULES when empty
    source_line: str = ""       # text of the offending line, for baselining
    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        if not self.severity:
            self.severity = RULES.get(self.rule, {}).get("severity", WARNING)

    @property
    def location(self) -> str:
        return f"{self.file}:{self.line}" if self.line else self.file

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "file": self.file, "line": self.line, "col": self.col,
             "message": self.message}
        if self.extra:
            d["extra"] = self.extra
        return d

    def __str__(self):
        return (f"{self.location}: {self.severity}: [{self.rule}] "
                f"{self.message}")


def summarize(findings: Sequence[Finding]) -> str:
    n_err = sum(1 for f in findings if f.severity == ERROR)
    n_warn = len(findings) - n_err
    return f"{len(findings)} finding(s): {n_err} error(s), {n_warn} warning(s)"


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == ERROR for f in findings)


# ---------------------------------------------------------------------------
# Inline suppressions:  # tpu-lint: disable=TS101[,TS102]
#   * on the offending line (or the decorated ``def`` line of the enclosing
#     traced function — ast_lint passes that line through as an alternate)
#   * whole file:        # tpu-lint: disable-file=TS102
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+)")


def parse_suppressions(source: str):
    """-> (line_no -> set(rules), file-wide set(rules)). 'all' wildcard ok."""
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group("rules").split(",")
                 if r.strip()}
        if m.group("scope"):
            file_wide |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
    return per_line, file_wide


def is_suppressed(finding: Finding, per_line: Dict[int, set],
                  file_wide: set, alt_lines: Sequence[int] = ()) -> bool:
    for rules in (file_wide,):
        if "ALL" in rules or finding.rule in rules:
            return True
    for ln in (finding.line, *alt_lines):
        rules = per_line.get(ln, ())
        if "ALL" in rules or finding.rule in rules:
            return True
    return False


# ---------------------------------------------------------------------------
# Baseline: accepted findings checked into the repo. Keys hash the rule +
# path + normalized source text of the flagged line, so ordinary edits that
# shift line numbers don't invalidate the baseline, while changing the
# flagged code itself does.
# ---------------------------------------------------------------------------

def baseline_key(finding: Finding) -> str:
    norm = " ".join(finding.source_line.split())
    h = hashlib.sha1(
        f"{finding.rule}|{finding.file}|{norm}".encode()).hexdigest()[:16]
    return h


def write_baseline(findings: Sequence[Finding], path: str):
    entries = [{"key": baseline_key(f), "rule": f.rule, "file": f.file,
                "line": f.line, "message": f.message} for f in findings]
    entries.sort(key=lambda e: (e["file"], e["rule"], e["key"]))
    with open(path, "w") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2)
        fh.write("\n")


def load_baseline(path: str) -> set:
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return set()
    return {e["key"] for e in data.get("findings", ())}


def apply_baseline(findings: Sequence[Finding],
                   baseline: set) -> List[Finding]:
    return [f for f in findings if baseline_key(f) not in baseline]
