"""Liveness-based HBM-footprint analysis (MEM3xx rules).

The memory half of the static PLAN_7B gate: roofline analysis bounds a
config's *time* before it runs; this module bounds its *memory*. Two entry
layers, mirroring ``analysis/sharding.py``:

* **jaxpr-level** (lazy jax import): ``peak_hbm_estimate`` walks the
  equations front-to-back tracking live buffer bytes — a var's buffer is
  freed after its last use, an output may reuse a dying same-layout input
  when the producing primitive's op-registry alias metadata permits
  donation (the DF006 contract from ``ops/registry.py``). Program inputs
  are only reusable when explicitly donated; a large input that dies at a
  donation-eligible equation *without* being donated is the MEM302
  missed-donation finding. ``check_hbm`` compares the peak against a
  budget (MEM301).
* **plan-level** (stdlib-only, no jax): ``check_plan_memory`` audits every
  ``PLAN_7B.json`` training variant against ``hbm_per_chip_gib`` —
  recorded per-chip byte categories are trusted at the recorded batch and
  scaled linearly in batch×seq otherwise (optimizer/param state constant,
  activations scale, the f32 grad shard held fixed). A variant already
  recorded infeasible (``fits_v5e_16gib: false``) is an honest documented
  baseline and does NOT error; overriding batch/seq re-opens the check.
  ``serving_bucket_report`` prices the gateway serving buckets (TP-sharded
  weights + per-rung KV cache) against the same budget.

Rules:
* MEM301 (error)   plan-over-hbm-budget.
* MEM302 (warning) missing-donation / remat opportunity.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

try:
    from .findings import ERROR, Finding, WARNING
    from . import sharding as _sharding
except ImportError:  # loaded standalone by tools/shard_check.py
    from findings import ERROR, Finding, WARNING  # type: ignore
    import sharding as _sharding  # type: ignore

__all__ = [
    "peak_hbm_estimate", "check_hbm", "variant_live_gib",
    "check_plan_memory", "serving_bucket_report",
]

GIB = 1024 ** 3

#: lax primitive -> framework op name, where they differ; the registry
#: speaks framework names (multiply), jaxprs speak lax names (mul).
_PRIM_TO_OP = {
    "mul": "multiply", "sub": "subtract", "div": "divide",
    "max": "maximum", "min": "minimum", "integer_pow": "pow",
    "logistic": "sigmoid",
}


def _donation_ops() -> Dict[str, dict]:
    try:
        from ..ops.registry import donatable_aliases
        return donatable_aliases()
    except Exception:  # standalone / partial-import contexts
        return {}


def _alias_for_prim(prim: str, donation_ops: Dict[str, dict]):
    return donation_ops.get(_PRIM_TO_OP.get(prim, prim))


def _aval_bytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()))
    return _sharding.nbytes(shape, getattr(aval, "dtype", "float32"))


# ---------------------------------------------------------------------------
# Jaxpr-level liveness walk
# ---------------------------------------------------------------------------

def peak_hbm_estimate(program, donate: Sequence[int] = (),
                      invar_shards: Optional[Sequence[int]] = None,
                      default_shards: int = 1) -> dict:
    """Estimate peak live HBM bytes of one jaxpr execution.

    Returns ``{"peak_bytes", "input_bytes", "output_bytes", "timeline",
    "missed_donations"}``. ``donate`` lists invar indices whose buffers
    the caller donates (jit ``donate_argnums``); intermediates are always
    reusable. The model charges each equation's transient as
    ``live + out_bytes - reuse_credit`` where the credit applies when a
    same-shape/dtype input dies at that equation and the primitive's
    registry alias metadata marks it donation-safe.

    Sharded per-chip mode (the runtime mesh gate): ``invar_shards`` is a
    per-invar shard degree (parallel to the jaxpr's invars) dividing that
    input's resident bytes, and ``default_shards`` divides every
    equation-produced buffer (the data-parallel degree activations shard
    over). Constvars and unlisted invars stay whole — replicated. The
    defaults reproduce the original whole-program accounting bit-for-bit.
    """
    from .dataflow import _closed  # lazy: pulls in jax
    try:
        from jax._src.core import DropVar, Literal, Var
    except ImportError:  # pragma: no cover
        from jax.core import DropVar, Literal, Var  # type: ignore

    closed = _closed(program)
    jaxpr = closed.jaxpr
    donation_ops = _donation_ops()
    donate = set(donate)

    n_eqns = len(jaxpr.eqns)
    last_use: Dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, Var):
                last_use[v] = i
    for v in jaxpr.outvars:
        if isinstance(v, Var):
            last_use[v] = n_eqns          # outputs live to the end

    donated_vars = {v for i, v in enumerate(jaxpr.invars) if i in donate}
    invar_index = {v: i for i, v in enumerate(jaxpr.invars)}

    divisor: Dict = {}
    if invar_shards is not None:
        for v, d in zip(jaxpr.invars, invar_shards):
            divisor[v] = max(1, int(d))
    boundary = set(jaxpr.invars) | set(jaxpr.constvars)

    def _vb(v) -> int:
        nb = _aval_bytes(v.aval)
        if v in divisor:
            return nb // divisor[v]
        if v in boundary:
            return nb
        return nb // max(1, int(default_shards))

    live = 0
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        live += _vb(v)
    input_bytes = live

    peak = live
    timeline = [(-1, live)]
    missed: List[dict] = []

    for i, eqn in enumerate(jaxpr.eqns):
        prim = str(eqn.primitive)
        out_bytes = sum(_vb(o) for o in eqn.outvars
                        if not isinstance(o, DropVar))
        dying = [v for v in dict.fromkeys(
                     x for x in eqn.invars if isinstance(x, Var))
                 if last_use.get(v) == i]
        dying_bytes = sum(_vb(v) for v in dying)

        credit = 0
        alias = _alias_for_prim(prim, donation_ops)
        if alias is not None and dying:
            out_layouts = [(tuple(o.aval.shape), str(o.aval.dtype))
                           for o in eqn.outvars
                           if not isinstance(o, DropVar)]
            for v in dying:
                layout = (tuple(v.aval.shape), str(v.aval.dtype))
                if layout not in out_layouts:
                    continue
                reusable = v not in invar_index or v in donated_vars
                if reusable:
                    credit = _vb(v)
                    out_layouts.remove(layout)
                else:
                    missed.append({
                        "invar": invar_index[v], "eqn": i,
                        "primitive": prim,
                        "bytes": _vb(v)})
        peak = max(peak, live + out_bytes - credit)
        live += out_bytes - dying_bytes
        timeline.append((i, live))

    output_bytes = sum(_vb(v) for v in jaxpr.outvars
                       if isinstance(v, Var))
    return {"peak_bytes": peak, "input_bytes": input_bytes,
            "output_bytes": output_bytes, "timeline": timeline,
            "missed_donations": missed}


def check_hbm(program, budget_gib: Optional[float] = None,
              donate: Sequence[int] = (),
              min_donation_bytes: int = 1 << 20) -> List[Finding]:
    """MEM301 (peak over budget) + MEM302 (missed donation) for a jaxpr."""
    est = peak_hbm_estimate(program, donate=donate)
    findings: List[Finding] = []
    if budget_gib is not None and est["peak_bytes"] > budget_gib * GIB:
        findings.append(Finding(
            "MEM301",
            f"estimated peak HBM {est['peak_bytes'] / GIB:.3f} GiB exceeds "
            f"the {budget_gib:.3f} GiB per-chip budget — the program OOMs "
            "on the first step",
            severity=ERROR,
            extra={"peak_bytes": est["peak_bytes"],
                   "budget_gib": budget_gib}))
    for m in est["missed_donations"]:
        if m["bytes"] < min_donation_bytes:
            continue
        findings.append(Finding(
            "MEM302",
            f"input #{m['invar']} ({m['bytes'] / (1 << 20):.1f} MiB) dies "
            f"at eqn #{m['eqn']} ({m['primitive']}) whose alias metadata "
            "permits buffer reuse, but the input is not donated — pass it "
            "in donate_argnums to drop the extra copy",
            line=m["eqn"], severity=WARNING, extra=dict(m)))
    return findings


# ---------------------------------------------------------------------------
# Plan-level audit (stdlib-only; consumes PLAN_7B.json records)
# ---------------------------------------------------------------------------

def _plan_chips(plan: dict) -> int:
    return _sharding.plan_mesh_size(plan)


def variant_live_gib(variant: dict, n_chips: int,
                     batch: Optional[int] = None,
                     seq: Optional[int] = None) -> dict:
    """Estimated per-chip live GiB for a training variant, optionally
    re-scaled to a different batch/seq.

    Trusts the recorded ``per_chip_bytes`` at the recorded shape (the
    recorded categories reproduce ``per_chip_live_gib`` exactly:
    ``args + temp + max(0, out - aliased)``). Under a batch/seq override,
    optimizer/param state (``arguments``) and the f32 grad shard stay
    constant while the remaining activation bytes scale linearly with
    batch×seq — the standard transformer activation model.
    """
    b0 = variant.get("batch", 16)
    s0 = variant.get("seq", 2048)
    b = batch if batch is not None else b0
    s = seq if seq is not None else s0
    ratio = (b * s) / float(b0 * s0)
    n_params = variant.get("n_params", 6738415616)
    grads = 4.0 * n_params / n_chips

    rec = variant.get("per_chip_bytes")
    if rec:
        state = float(rec["arguments"])
        act = float(rec["temp"]) + max(
            0.0, float(rec["outputs"]) - float(rec["aliased"]))
        act_var = max(0.0, act - grads)
        live = state + grads + act_var * ratio
        basis = "recorded" if ratio == 1.0 else "scaled"
    else:
        # analytic fallback: state by stage, activations from dims
        stage = str(variant.get("variant", "s3"))
        if stage.startswith("s2"):
            state = 2.0 * n_params + 12.0 * n_params / n_chips
        else:
            state = 14.0 * n_params / n_chips
        act_var = 6.0 * n_params / n_chips  # coarse: grads-scale workspace
        live = state + grads + act_var * ratio
        basis = "analytic"
    return {"live_gib": live / GIB, "basis": basis, "batch": b, "seq": s,
            "ratio": ratio}


def check_plan_memory(plan: dict, hbm_gib: Optional[float] = None,
                      batch: Optional[int] = None,
                      seq: Optional[int] = None,
                      strict: bool = False,
                      rows: Optional[list] = None,
                      file: str = "<plan>") -> List[Finding]:
    """MEM301/MEM302 over every training variant of a PLAN_7B dict.

    A variant recorded ``fits_v5e_16gib: false`` at its recorded shape is
    a documented-infeasible baseline: reported in ``rows`` but not an
    error (``strict=True`` errors anyway). Overriding batch/seq always
    re-opens the check — that is the "deliberately oversubscribed
    variant" path the gate exists for.
    """
    budget = hbm_gib if hbm_gib is not None else float(
        plan.get("hbm_per_chip_gib", 16.0))
    n_chips = _plan_chips(plan)
    overridden = batch is not None or seq is not None
    variants = list(plan.get("variants", ()))
    findings: List[Finding] = []
    fits_map = {}

    for var in variants:
        name = var.get("name", var.get("variant", "?"))
        est = variant_live_gib(var, n_chips, batch=batch, seq=seq)
        over = est["live_gib"] > budget
        fits_map[name] = (var, est, over)
        if rows is not None:
            rows.append({"variant": name, "batch": est["batch"],
                         "seq": est["seq"], "remat": var.get("remat"),
                         "live_gib": round(est["live_gib"], 3),
                         "basis": est["basis"], "fits": not over})
        if not over:
            continue
        documented = (not overridden
                      and var.get("fits_v5e_16gib") is False)
        if documented and not strict:
            continue
        findings.append(Finding(
            "MEM301",
            f"variant '{name}' ({est['basis']}, batch {est['batch']} x "
            f"seq {est['seq']}) needs {est['live_gib']:.2f} GiB/chip but "
            f"the budget is {budget:.2f} GiB — OOM before step 1",
            file=file, severity=ERROR,
            extra={"variant": name, "live_gib": est["live_gib"],
                   "budget_gib": budget, "basis": est["basis"]}))

    # MEM302: an over-budget variant whose sibling at the same shape fits
    # — the remat/sharding headroom exists and is not taken.
    for name, (var, est, over) in fits_map.items():
        if not over:
            continue
        for other, (ovar, oest, oover) in fits_map.items():
            if other == name or oover:
                continue
            if (oest["batch"], oest["seq"]) != (est["batch"], est["seq"]):
                continue
            findings.append(Finding(
                "MEM302",
                f"variant '{name}' is over budget at "
                f"{est['live_gib']:.2f} GiB but sibling '{other}' "
                f"(remat={ovar.get('remat')}, "
                f"variant={ovar.get('variant')}) fits at "
                f"{oest['live_gib']:.2f} GiB — remat/sharding headroom "
                "exists and is not taken",
                file=file, severity=WARNING,
                extra={"variant": name, "sibling": other}))
            break
    return findings


# ---------------------------------------------------------------------------
# Gateway serving buckets
# ---------------------------------------------------------------------------

def _serving_rungs(seq_max: int, rungs=None) -> List[int]:
    if rungs:
        return sorted(int(r) for r in rungs)
    try:
        from ..perf.buckets import BucketLadder
        return list(BucketLadder.pow2(lo=128, hi=seq_max).buckets)
    except Exception:  # standalone CLI: equivalent pow2 ladder
        out, b = [], 128
        while b < seq_max:
            out.append(b)
            b *= 2
        out.append(seq_max)
        return out


def serving_bucket_report(plan: dict, mesh_size: Optional[int] = None,
                          hbm_gib: Optional[float] = None,
                          dims: Optional[dict] = None,
                          max_batch: int = 8, rungs=None,
                          kv_dtype_bytes: int = 2,
                          file: str = "<plan>") -> dict:
    """Price the gateway serving buckets against the per-chip budget.

    Serving shards tensor-parallel over the mesh: bf16 weights 2P/N per
    chip, attention heads split N-ways (SH201 when the head count does
    not divide), and per-sequence KV cache 2·L·S·H·kv_bytes/N per rung.
    Returns ``{"rows", "findings"}``; over-budget rungs flag MEM301.
    """
    d = dict(_sharding.LLAMA7B_DIMS, **(dims or {}))
    n = mesh_size or _plan_chips(plan)
    budget = hbm_gib if hbm_gib is not None else float(
        plan.get("hbm_per_chip_gib", 16.0))
    n_params = None
    seq_max = 0
    for var in plan.get("variants", ()):
        n_params = n_params or var.get("n_params")
        seq_max = max(seq_max, var.get("seq", 0))
    n_params = n_params or 6738415616
    seq_max = seq_max or 2048

    findings: List[Finding] = []
    for key in ("heads", "kv_heads"):
        if d[key] % n:
            findings.append(Finding(
                "SH201",
                f"serving TP shards attention over {n} chips but "
                f"{key}={d[key]} is not divisible by {n}",
                file=file, severity=ERROR,
                extra={"param": key, "degree": n}))

    weights = 2.0 * n_params / n
    rows = []
    for s in _serving_rungs(seq_max, rungs):
        kv_per_seq = 2.0 * d["L"] * s * d["H"] * kv_dtype_bytes / n
        logits = max_batch * d["V"] * 4.0
        live = weights + max_batch * kv_per_seq + logits
        fits = live <= budget * GIB
        rows.append({"bucket": s, "max_batch": max_batch,
                     "weights_gib": round(weights / GIB, 3),
                     "kv_gib": round(max_batch * kv_per_seq / GIB, 3),
                     "live_gib": round(live / GIB, 3), "fits": fits})
        if not fits:
            findings.append(Finding(
                "MEM301",
                f"serving bucket seq={s} at batch {max_batch} needs "
                f"{live / GIB:.2f} GiB/chip (weights "
                f"{weights / GIB:.2f} + KV "
                f"{max_batch * kv_per_seq / GIB:.2f}) over the "
                f"{budget:.2f} GiB budget",
                file=file, severity=ERROR,
                extra={"bucket": s, "live_gib": live / GIB}))
    return {"rows": rows, "findings": findings}
