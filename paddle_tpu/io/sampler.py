"""Samplers.

Reference: python/paddle/io/dataloader/sampler.py — Sampler, SequenceSampler,
RandomSampler, WeightedRandomSampler; batch_sampler.py — BatchSampler,
DistributedBatchSampler.
"""
from __future__ import annotations

import math
from typing import Iterator, Optional

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def _rng(self):
        if self.generator is not None:
            # reference accepts a generator callable yielding indices
            return None
        from ..core import random as random_mod
        key = random_mod.default_generator().next_key()
        return np.random.RandomState(int(np.asarray(key)[-1]) % (2 ** 31))

    def __iter__(self):
        if self.generator is not None:
            for _ in range(self.num_samples):
                try:
                    yield next(self.generator)
                except StopIteration:
                    return
            return
        rng = self._rng()
        n = len(self.data_source)
        if self.replacement:
            yield from rng.randint(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__()
        self.weights = np.asarray(weights, dtype=np.float64)
        if self.weights.ndim != 1:
            raise ValueError("weights should be a 1-d sequence")
        self.num_samples = num_samples
        self.replacement = replacement
        if not replacement and num_samples > len(self.weights):
            raise ValueError("num_samples should not be greater than the "
                             "number of weights when replacement is False")

    def __iter__(self):
        from ..core import random as random_mod
        key = random_mod.default_generator().next_key()
        rng = np.random.RandomState(int(np.asarray(key)[-1]) % (2 ** 31))
        p = self.weights / self.weights.sum()
        idx = rng.choice(len(self.weights), size=self.num_samples,
                         replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


def _chunk_indices(indices, batch_size, drop_last):
    """Shared batching loop for all batch samplers."""
    batch = []
    for idx in indices:
        batch.append(idx)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch


class BatchSampler(Sampler):
    """io/dataloader/batch_sampler.py BatchSampler analog."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__()
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle \
                else SequenceSampler(dataset)
        elif dataset is not None:
            raise ValueError("dataset should not be set when sampler is given")
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self.shuffle = shuffle

    def __iter__(self):
        return _chunk_indices(self.sampler, self.batch_size, self.drop_last)

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """io/dataloader/batch_sampler.py DistributedBatchSampler analog: each
    rank samples its 1/nranks slice; set_epoch reseeds the shuffle.

    Single-controller note: with a global mesh the DataLoader usually feeds
    the full global batch and shards it over dp; this sampler serves the
    per-process (multi-host DCN) case where every host loads its own slice.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self.shuffle = bool(shuffle)
        if num_replicas is None:
            import jax
            num_replicas = jax.process_count()
        if rank is None:
            import jax
            rank = jax.process_index()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        # pad to make evenly divisible, then take this rank's slice
        indices += indices[:(self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        yield from _chunk_indices(indices, self.batch_size, self.drop_last)

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch


class SubsetRandomSampler(Sampler):
    """ref io/sampler.py SubsetRandomSampler: random permutation of a fixed
    index subset each epoch."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        from ..core import random as random_mod
        key = random_mod.default_generator().next_key()
        rng = np.random.RandomState(int(np.asarray(key)[-1]) % (2 ** 31))
        for i in rng.permutation(len(self.indices)):
            yield self.indices[i]

    def __len__(self):
        return len(self.indices)
