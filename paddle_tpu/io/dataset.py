"""Datasets.

Reference: python/paddle/io/dataset.py + io/dataloader/dataset.py — Dataset,
IterableDataset, TensorDataset, ComposeDataset, ChainDataset, Subset,
random_split, ConcatDataset.
"""
from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np


class Dataset:
    """Map-style dataset (io/dataloader/dataset.py Dataset analog)."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__getitem__", self.__class__.__name__))

    def __len__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__len__", self.__class__.__name__))


class IterableDataset(Dataset):
    """Stream-style dataset (IterableDataset analog)."""

    def __iter__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__iter__", self.__class__.__name__))

    def __getitem__(self, idx):
        raise RuntimeError("'__getitem__' not available for IterableDataset")

    def __len__(self):
        raise RuntimeError("'__len__' not available for IterableDataset")


class TensorDataset(Dataset):
    """Wrap a list of tensors; sample i = tuple of tensor[i] slices.

    Samples are materialized to host numpy once at construction so that
    multiprocess workers (fork start method) never touch jax — forked
    children deadlock on JAX's internal threads. The main process still
    receives Tensor samples for API parity; workers get numpy (which the
    default collate produces Tensors from anyway)."""

    def __init__(self, tensors):
        from ..core.tensor import Tensor
        self.tensors = [t if isinstance(t, Tensor) else Tensor(t)
                        for t in tensors]
        n = self.tensors[0].shape[0]
        for t in self.tensors:
            if t.shape[0] != n:
                raise ValueError("all tensors must share dim 0")
        self._np = [np.asarray(t.numpy()) for t in self.tensors]

    def __getitem__(self, idx):
        from .dataloader import get_worker_info
        rows = tuple(a[idx] for a in self._np)
        if get_worker_info() is not None:
            return rows  # numpy inside workers: fork-safe
        from ..core.tensor import Tensor
        return tuple(Tensor(r) for r in rows)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    """Zip datasets: sample i = flattened fields of every dataset's sample i."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets should not be empty")
        n = len(self.datasets[0])
        for d in self.datasets:
            if isinstance(d, IterableDataset):
                raise TypeError("ComposeDataset does not support "
                                "IterableDataset")
            if len(d) != n:
                raise ValueError("lengths of datasets should be same")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            s = d[idx]
            sample.extend(s if isinstance(s, (list, tuple)) else [s])
        return tuple(sample)


class ChainDataset(IterableDataset):
    """Concatenate stream datasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        for d in self.datasets:
            if not isinstance(d, IterableDataset):
                raise TypeError("ChainDataset only supports IterableDataset")

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    """Concatenate map datasets (io/dataloader/dataset.py ConcatDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets should not be empty")
        for d in self.datasets:
            if isinstance(d, IterableDataset):
                raise TypeError("ConcatDataset does not support "
                                "IterableDataset")
        self.cumulative_sizes = np.cumsum(
            [len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self.cumulative_sizes, idx, side="right"))
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None) -> List[Subset]:
    """io/dataset.py random_split analog. lengths: sizes or fractions."""
    n = len(dataset)
    if all(0.0 < l < 1.0 for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        sizes = [int(np.floor(n * l)) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != n:
        raise ValueError("Sum of input lengths does not equal the length of "
                         "the input dataset!")
    from ..core import random as random_mod
    rng = np.random.RandomState(random_mod.default_generator().initial_seed()
                                % (2 ** 31))
    perm = rng.permutation(n).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out
