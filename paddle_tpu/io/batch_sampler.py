"""Batch samplers (reference: python/paddle/io/dataloader/batch_sampler.py).
Implementations live in sampler.py; this module mirrors the reference layout."""
from .sampler import BatchSampler, DistributedBatchSampler

__all__ = ["BatchSampler", "DistributedBatchSampler"]
