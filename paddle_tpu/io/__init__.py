"""paddle.io analog (reference: python/paddle/io — SURVEY.md §2.17)."""
from .collate import default_collate_fn, default_convert_fn
from .dataloader import DataLoader, WorkerInfo, get_worker_info
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)

__all__ = [
    "default_collate_fn", "default_convert_fn", "DataLoader", "WorkerInfo",
    "get_worker_info", "ChainDataset", "ComposeDataset", "ConcatDataset",
    "Dataset", "IterableDataset", "Subset", "TensorDataset", "random_split",
    "BatchSampler", "DistributedBatchSampler", "RandomSampler", "Sampler",
    "SubsetRandomSampler",
    "SequenceSampler", "WeightedRandomSampler",
]
