"""Batch collation.

Reference: python/paddle/io/dataloader/collate.py — default_collate_fn
(stack samples into batched tensors field-wise), default_convert_fn.

TPU note: workers collate to NUMPY (picklable, shared-memory friendly); the
main process converts to device tensors in one host-to-device transfer per
field — minimizing H2D round trips is the TPU analog of the reference's
pinned-memory fast path.
"""
from __future__ import annotations

import numbers

import numpy as np


def default_collate_fn(batch):
    """Stack a list of samples field-wise (collate.py analog)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, numbers.Number):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn(fields) for fields in zip(*batch)]
    # Tensor samples (TensorDataset): stack on host
    from ..core.tensor import Tensor
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.numpy()) for s in batch], axis=0)
    raise TypeError(f"batch data can only contains: tensor, numpy.ndarray, "
                    f"dict, list, number, but got {type(sample)}")


def default_convert_fn(batch):
    from ..core.tensor import Tensor
    if isinstance(batch, (Tensor, np.ndarray)):
        return batch
    if isinstance(batch, (str, bytes)):
        return batch
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return [default_convert_fn(d) for d in batch]
    return batch


def to_tensor_tree(batch):
    """numpy tree -> Tensor tree (one H2D per leaf)."""
    from ..core.tensor import Tensor
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, dict):
        return {k: to_tensor_tree(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return [to_tensor_tree(v) for v in batch]
    return batch
