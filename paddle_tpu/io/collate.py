"""Batch collation.

Reference: python/paddle/io/dataloader/collate.py — default_collate_fn
(stack samples into batched tensors field-wise), default_convert_fn.

TPU note: workers collate to NUMPY (picklable, shared-memory friendly); the
main process converts to device tensors in one host-to-device transfer per
field — minimizing H2D round trips is the TPU analog of the reference's
pinned-memory fast path.
"""
from __future__ import annotations

import numbers

import numpy as np


def default_collate_fn(batch):
    """Stack a list of samples field-wise (collate.py analog)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, numbers.Number):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn(fields) for fields in zip(*batch)]
    # Tensor samples (TensorDataset): stack on host
    from ..core.tensor import Tensor
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.numpy()) for s in batch], axis=0)
    raise TypeError(f"batch data can only contains: tensor, numpy.ndarray, "
                    f"dict, list, number, but got {type(sample)}")


def default_convert_fn(batch):
    from ..core.tensor import Tensor
    if isinstance(batch, (Tensor, np.ndarray)):
        return batch
    if isinstance(batch, (str, bytes)):
        return batch
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return [default_convert_fn(d) for d in batch]
    return batch


def _as_lists(batch):
    """Normalize tuples to lists (the tree shape to_tensor_tree always
    produced) so the coalesced transfer round-trips the same structure."""
    if isinstance(batch, dict):
        return {k: _as_lists(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return [_as_lists(v) for v in batch]
    return batch


def to_tensor_tree(batch):
    """numpy tree -> device Tensor tree in ONE coalesced transfer.

    Every array leaf in the batch ships in a single batched
    ``jax.device_put`` call (perf.prefetch.coalesced_device_put): one H2D
    round trip per BATCH, not one per field."""
    from ..perf.prefetch import coalesced_device_put
    return coalesced_device_put(_as_lists(batch))
