"""DataLoader.

Reference: python/paddle/io/ — DataLoader over Dataset/BatchSampler with
single-process iteration (dataloader_iter.py:150) and multi-process workers
feeding shared-memory queues (dataloader_iter.py:358, worker.py), backed by
C++ blocking queues (fluid/imperative/data_loader.cc).

TPU-native redesign: workers are OS processes producing NUMPY batches over
multiprocessing queues (pickle/shm transport); the main process performs one
host-to-device transfer per field. The reference's C++ blocking-queue +
mmap-allocator tier exists to feed GPUs at high rate from CPython — here the
device feed is XLA's async transfer engine, so the host tier stays lean
(ordered reassembly + prefetch window, same semantics as worker.py).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
import traceback
from typing import Any, Callable, Optional

import numpy as np

from .batch_sampler import BatchSampler, DistributedBatchSampler  # noqa: F401
from .collate import default_collate_fn, default_convert_fn, to_tensor_tree
from .dataset import Dataset, IterableDataset

_worker_info = threading.local()


def _ndarray_leaves(tree):
    """Yield every np.ndarray leaf of a collated (dict/list/tuple) batch."""
    if isinstance(tree, np.ndarray):
        yield tree
    elif isinstance(tree, dict):
        for v in tree.values():
            yield from _ndarray_leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _ndarray_leaves(v)


def _map_ndarray_leaves(tree, fn):
    if isinstance(tree, np.ndarray):
        return fn(tree)
    if isinstance(tree, dict):
        return {k: _map_ndarray_leaves(v, fn) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_map_ndarray_leaves(v, fn) for v in tree)
    return tree


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info() -> Optional[WorkerInfo]:
    """io get_worker_info analog (valid inside worker processes)."""
    return getattr(_worker_info, "info", None)


class _WorkerEnd:
    pass


def _worker_loop(dataset, index_queue, result_queue, collate_fn, init_fn,
                 worker_id, num_workers, base_seed, iterable, drop_last):
    try:
        np.random.seed((base_seed + worker_id) % (2 ** 31))
        _worker_info.info = WorkerInfo(worker_id, num_workers, dataset,
                                       base_seed + worker_id)
        if init_fn is not None:
            init_fn(worker_id)
        if iterable:
            it = iter(dataset)
        while True:
            task = index_queue.get()
            if task is None:
                break
            seq, indices = task
            try:
                if iterable:
                    samples = []
                    for _ in indices:
                        try:
                            samples.append(next(it))
                        except StopIteration:
                            break
                    if not samples or (drop_last and
                                       len(samples) < len(indices)):
                        result_queue.put((seq, _WorkerEnd(), None))
                        continue
                else:
                    samples = [dataset[i] for i in indices]
                batch = collate_fn(samples)
                result_queue.put((seq, batch, None))
            except Exception:  # noqa: BLE001 — forwarded to the main process
                result_queue.put((seq, None, traceback.format_exc()))
    except KeyboardInterrupt:
        pass


class _MultiprocessIter:
    """Ordered multi-worker iterator (dataloader_iter.py:358 analog)."""

    def __init__(self, loader, batches):
        self._loader = loader
        self._batches = iter(batches)
        self._iterable = isinstance(loader.dataset, IterableDataset)
        # fork matches the reference's worker model and is fast, but a forked
        # child must not touch jax (JAX threads + fork can deadlock) — keep
        # worker datasets numpy-only, or set FLAGS_dataloader_mp_context=spawn
        from ..core.flags import get_flag
        ctx = mp.get_context(get_flag("FLAGS_dataloader_mp_context"))
        self._result_queue = ctx.Queue()
        self._workers = []
        self._index_queues = []
        from ..core import random as random_mod
        base_seed = random_mod.default_generator().initial_seed() + 1

        n = loader.num_workers
        for wid in range(n):
            iq = ctx.Queue()
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, iq, self._result_queue,
                      loader.collate_fn or default_collate_fn,
                      loader.worker_init_fn, wid, n, base_seed,
                      self._iterable, loader.drop_last),
                daemon=True)
            w.start()
            self._workers.append(w)
            self._index_queues.append(iq)

        self._seq_send = 0
        self._seq_recv = 0
        self._cache = {}
        self._seq_wid = {}
        self._alive = list(range(n))
        self._rr = 0
        self._outstanding = 0
        self._shutdown = False
        # prefetch window
        for _ in range(n * loader.prefetch_factor):
            self._dispatch()

    def __iter__(self):
        return self

    def _dispatch(self):
        if not self._alive:
            return False
        try:
            indices = next(self._batches)
        except StopIteration:
            return False
        wid = self._alive[self._rr % len(self._alive)]
        self._rr += 1
        self._index_queues[wid].put((self._seq_send, indices))
        self._seq_wid[self._seq_send] = wid
        self._seq_send += 1
        self._outstanding += 1
        return True

    def _get_result(self):
        """Poll the result queue, watching worker liveness so a crashed
        worker (OOM-kill, segfault) surfaces as an error instead of a hang
        (the reference watches worker exit codes the same way, worker.py)."""
        deadline = None
        if self._loader.timeout:
            import time
            deadline = time.monotonic() + self._loader.timeout
        while True:
            try:
                return self._result_queue.get(timeout=1.0)
            except queue_mod.Empty:
                dead = [w.pid for w in self._workers if not w.is_alive()]
                if dead and self._outstanding > 0:
                    self._stop()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited unexpectedly")
                if deadline is not None:
                    import time
                    if time.monotonic() > deadline:
                        self._stop()
                        raise RuntimeError(
                            f"DataLoader timed out after "
                            f"{self._loader.timeout}s waiting for a batch")

    def __next__(self):
        from ..resilience.chaos import fault_point
        fault_point("dataloader.next")  # chaos drills; no-op unarmed
        while True:
            if self._outstanding == 0:
                self._stop()
                raise StopIteration
            while self._seq_recv not in self._cache:
                seq, batch, err = self._get_result()
                self._cache[seq] = (batch, err)
            batch, err = self._cache.pop(self._seq_recv)
            wid = self._seq_wid.pop(self._seq_recv)
            self._seq_recv += 1
            self._outstanding -= 1
            if err is not None:
                self._stop()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            if isinstance(batch, _WorkerEnd):
                # this worker's stream is exhausted: stop feeding it but keep
                # the remaining workers' pipelines full
                if wid in self._alive:
                    self._alive.remove(wid)
                self._dispatch()
                continue
            self._dispatch()
            return self._loader._postprocess(batch)

    def _stop(self):
        if self._shutdown:
            return
        self._shutdown = True
        for iq in self._index_queues:
            try:
                iq.put(None)
            except Exception:  # noqa: BLE001
                pass
        for w in self._workers:
            w.join(timeout=2)
            if w.is_alive():
                w.terminate()

    def __del__(self):
        self._stop()


class _SingleProcessIter:
    """dataloader_iter.py:150 analog."""

    def __init__(self, loader, batches):
        self._loader = loader
        self._batches = iter(batches)
        self._dataset = loader.dataset
        self._collate = loader.collate_fn or default_collate_fn
        self._iterable = isinstance(loader.dataset, IterableDataset)
        if self._iterable:
            self._stream = iter(self._dataset)

    def __iter__(self):
        return self

    def __next__(self):
        from ..resilience.chaos import fault_point
        fault_point("dataloader.next")  # chaos drills; no-op unarmed
        indices = next(self._batches)
        if self._iterable:
            samples = list(itertools.islice(self._stream, len(indices)))
            if not samples or (self._loader.drop_last and
                               len(samples) < len(indices)):
                raise StopIteration
        else:
            samples = [self._dataset[i] for i in indices]
        return self._loader._postprocess(self._collate(samples))


class _InfiniteCounter:
    """Index stream for IterableDataset (indices are just batch sizes)."""

    def __init__(self, batch_size):
        self.batch_size = batch_size

    def __iter__(self):
        while True:
            yield list(range(self.batch_size))


import atexit as _atexit
import weakref as _weakref

_LIVE_READERS = _weakref.WeakSet()  # active _BufferReaders


def _drain_readers_at_exit():
    """Close every live buffer queue before interpreter finalization: a
    feeder thread parked inside the native condvar at exit would otherwise
    be force-unwound through C++ frames (pthread_exit during take_gil),
    aborting with 'FATAL: exception not rethrown'."""
    for reader in list(_LIVE_READERS):
        try:
            reader._q.close()
            reader._thread.join(timeout=2.0)
        except Exception:  # noqa: BLE001 — best-effort shutdown
            pass


_atexit.register(_drain_readers_at_exit)


class _BufferReader:
    """Device-side prefetch buffer (the reference's use_buffer_reader: C++
    blocking queue fed by a reader thread, fluid/imperative/data_loader.cc).

    A daemon thread drives the underlying iterator — including the
    host-to-device transfer in DataLoader._postprocess — and pushes finished
    batches into a native BlockingQueue (csrc/native.cc), so transfer and
    Python-side decode overlap with the training step consuming batches.
    """

    def __init__(self, it, depth=2):
        from ..core.native import BlockingQueue, stat_update
        self._q = BlockingQueue(depth)
        self._err = None
        self._stat_update = stat_update
        _LIVE_READERS.add(self)

        def _feed():
            try:
                while True:
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    self._stat_update("dataloader_buffered_batches", 1)
                    try:
                        self._q.push(batch)
                    except BrokenPipeError:
                        break  # consumer dropped the iterator
            except BaseException as e:  # noqa: BLE001 — surfaced on pop
                self._err = e
            finally:
                self._q.close()

        self._thread = threading.Thread(target=_feed, daemon=True,
                                        name="paddle_tpu_buffer_reader")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = self._q.pop()
        except StopIteration:
            if self._err is not None:
                raise self._err from None
            raise
        self._stat_update("dataloader_buffered_batches", -1)
        return batch

    def __del__(self):  # pragma: no cover
        try:
            self._q.close()
            self._q.release()
        except Exception:
            pass


class DataLoader:
    """paddle.io.DataLoader analog."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, prefetch_to_device=False,
                 batch_buckets=None):
        """``prefetch_to_device=True`` replaces the host-side buffer reader
        with perf.prefetch.DevicePrefetcher: a background thread lands
        batch N+1 on device (one coalesced transfer) while the consumer is
        still stepping on batch N. ``batch_buckets`` (a perf.buckets ladder
        spec: "pow2", "fixed:K" needs no hi here — capped at batch_size —
        or an explicit list) pads the TAIL batch up to a bucket rung by
        repeating the last sample, so the final partial batch reuses an
        already-compiled program instead of forcing a fresh XLA compile.
        Padding duplicates samples: with ``batch_buckets`` prefer mean-type
        losses (a sum-type loss counts the duplicated rows twice)."""
        self.dataset = dataset
        self.collate_fn = collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = int(prefetch_factor)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.return_list = return_list
        self.return_numpy = False
        self.use_buffer_reader = bool(use_buffer_reader)
        self.prefetch_to_device = bool(prefetch_to_device)

        self.drop_last = bool(drop_last)
        if isinstance(dataset, IterableDataset):
            if batch_sampler is not None or shuffle:
                raise ValueError("IterableDataset does not support "
                                 "batch_sampler or shuffle")
            self.batch_sampler = None
            self.batch_size = batch_size
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        else:
            if batch_size is None:
                raise ValueError("batch_size should be given")
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size
        from ..perf.buckets import resolve_ladder
        hi = self.batch_size if isinstance(self.batch_size, int) else None
        self._batch_ladder = resolve_ladder(batch_buckets, hi)

    def _batches(self):
        if self.batch_sampler is None:
            return _InfiniteCounter(self.batch_size)
        return self.batch_sampler

    def _pad_tail_batch(self, batch):
        """Pad a partial batch's leading dim up to the bucket rung by
        repeating the last sample (host-side, numpy)."""
        sizes = {a.shape[0] for a in _ndarray_leaves(batch) if a.ndim > 0}
        if len(sizes) != 1:
            return batch  # ragged or array-free batch: leave it alone
        b = sizes.pop()
        target = self._batch_ladder.bucket(b)
        if target == b:
            return batch
        pad = target - b

        def pad_leaf(x):
            if isinstance(x, np.ndarray) and x.ndim > 0:
                return np.concatenate(
                    [x, np.repeat(x[-1:], pad, axis=0)], axis=0)
            return x

        from ..observability.metrics import get_registry
        get_registry().counter(
            "dataloader.bucket_pad_rows",
            "duplicated rows added to tail batches by bucket "
            "padding").inc(pad)
        return _map_ndarray_leaves(batch, pad_leaf)

    def _host_postprocess(self, batch):
        """Host-side (numpy) half of batch postprocessing — runs on the
        iterator thread; the device transfer can then happen elsewhere
        (the prefetcher thread)."""
        if self._batch_ladder is not None:
            batch = self._pad_tail_batch(batch)
        return batch

    def _to_device(self, batch):
        return to_tensor_tree(batch)

    def _postprocess(self, batch):
        batch = self._host_postprocess(batch)
        if self.return_numpy:
            return batch
        if self.prefetch_to_device:
            # stay numpy here: the DevicePrefetcher's feeder thread owns
            # the (coalesced) host-to-device transfer
            return batch
        return self._to_device(batch)

    def __iter__(self):
        batches = self._batches()
        if self.num_workers == 0:
            it = _SingleProcessIter(self, batches)
        else:
            it = _MultiprocessIter(self, batches)
        if self.prefetch_to_device and not self.return_numpy:
            from ..perf.prefetch import DevicePrefetcher
            return DevicePrefetcher(it, depth=max(2, self.prefetch_factor),
                                    transfer=self._to_device)
        if self.use_buffer_reader:
            return _BufferReader(it, depth=max(2, self.prefetch_factor))

        class _Iter:
            def __iter__(self_i):
                return self_i

            def __next__(self_i):
                return next(it)

        return _Iter()

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)
