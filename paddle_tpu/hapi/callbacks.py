"""hapi training callbacks.

Reference: python/paddle/hapi/callbacks.py — Callback base with the
train/eval/predict begin/end + epoch/batch hooks, config_callbacks assembly,
and the stock ProgBarLogger / ModelCheckpoint / LRScheduler / EarlyStopping.
"""
from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np


class Callback:
    """callbacks.py Callback analog: all hooks are optional overrides."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # train
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    # eval
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    # predict
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(_fmt(x) for x in np.ravel(np.asarray(v))) + "]"
    return str(v)


class ProgBarLogger(Callback):
    """callbacks.py ProgBarLogger analog: per-log_freq step lines + epoch
    summaries (plain lines rather than a terminal progress bar — logs must
    stay readable when collated across ranks by the launcher)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._step = 0
        self._t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self.verbose == 0 or not logs:
            return
        if self._step % self.log_freq == 0 or (
                self.steps and self._step == self.steps):
            msg = " - ".join(f"{k}: {_fmt(v)}" for k, v in logs.items())
            print(f"step {self._step}/{self.steps or '?'} - {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose and logs:
            dt = time.time() - self._t0
            msg = " - ".join(f"{k}: {_fmt(v)}" for k, v in logs.items())
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {msg}")

    def on_eval_begin(self, logs=None):
        self._eval_t0 = time.time()
        if self.verbose:
            n = (logs or {}).get("steps")
            print(f"Eval begin ({n or '?'} steps)")

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            dt = time.time() - self._eval_t0
            msg = " - ".join(f"{k}: {_fmt(v)}" for k, v in logs.items()
                             if k != "batch_size")
            print(f"Eval done ({dt:.1f}s) - {msg}")


class ModelCheckpoint(Callback):
    """callbacks.py ModelCheckpoint analog: save every save_freq epochs into
    save_dir/{epoch}, and save_dir/final at train end."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            print(f"save checkpoint at {os.path.abspath(path)}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            path = os.path.join(self.save_dir, "final")
            print(f"save checkpoint at {os.path.abspath(path)}")
            self.model.save(path)


class LRScheduler(Callback):
    """callbacks.py LRScheduler analog: steps the optimizer's lr scheduler
    per epoch (default) or per batch."""

    def __init__(self, by_step: bool = False, by_epoch: bool = True):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as _Sched
        opt = getattr(self.model, "_optimizer", None)
        sched = getattr(opt, "_learning_rate", None)
        return sched if isinstance(sched, _Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            sched = self._sched()
            if sched is not None:
                sched.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            sched = self._sched()
            if sched is not None:
                sched.step()


class EarlyStopping(Callback):
    """callbacks.py EarlyStopping analog: monitors an eval metric; stops
    training (model.stop_training) after `patience` evals without
    min_delta improvement; optionally restores/keeps best weights."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "min":
            self.monitor_op = np.less
        elif mode == "max":
            self.monitor_op = np.greater
        else:
            self.monitor_op = (np.greater if ("acc" in monitor
                                              or monitor.startswith("fmeasure"))
                               else np.less)
        self.min_delta *= 1 if self.monitor_op == np.greater else -1

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline
        else:
            self.best_value = np.inf if self.monitor_op == np.less else -np.inf

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple, np.ndarray)):
            current = float(np.ravel(np.asarray(current))[0])
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None:
                self.best_weights = {
                    k: np.asarray(v._data).copy()
                    for k, v in self.model.network.state_dict().items()}
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Epoch early stopped: best {self.monitor} = "
                      f"{self.best_value:.5f}")

    def on_train_end(self, logs=None):
        # restore the best snapshot so training ends at the best eval point
        if self.save_best_model and self.best_weights is not None:
            from ..core.tensor import Tensor
            self.model.network.set_state_dict(
                {k: Tensor(v) for k, v in self.best_weights.items()})


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    """callbacks.py config_callbacks analog."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs,
                    "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst
