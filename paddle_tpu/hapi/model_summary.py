"""paddle.summary analog.

Reference: python/paddle/hapi/model_summary.py — per-layer table of output
shapes + parameter counts via forward hooks, and total/trainable counts +
memory estimate.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer


def _dtype_size(dtype_str: str) -> int:
    if "64" in dtype_str:
        return 8
    if "16" in dtype_str or "bfloat16" in dtype_str:
        return 2
    if "8" in dtype_str or "bool" in dtype_str:
        return 1
    return 4


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Prints the layer table; returns {'total_params': n, 'trainable_params': n}."""
    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or a concrete input")
        sizes = (list(input_size) if isinstance(input_size, list)
                 else [input_size])
        if sizes and isinstance(sizes[0], int):
            sizes = [tuple(sizes)]
        dtypes = dtypes or ["float32"] * len(sizes)
        if isinstance(dtypes, str):
            dtypes = [dtypes] * len(sizes)
        inputs = []
        for shape, dt in zip(sizes, dtypes):
            shape = tuple(2 if (d is None or d < 0) else d for d in shape)
            np_dt = np.dtype("float32" if dt == "bfloat16" else dt)
            t = Tensor(np.zeros(shape, dtype=np_dt))
            if dt == "bfloat16":
                t = t.astype("bfloat16")
            inputs.append(t)
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, ins, outs):
            out = outs[0] if isinstance(outs, (list, tuple)) else outs
            shape = tuple(out.shape) if hasattr(out, "shape") else ()
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr.parameters(include_sublayers=False))
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}",
                         str(shape), n_params))
        return hook

    for name, sub in net.named_sublayers():
        if next(iter(sub.children()), None) is None:  # leaf layers only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    was_training = net.training if hasattr(net, "training") else None
    net.eval()
    from ..autograd import no_grad
    try:
        with no_grad():
            net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = 0
    trainable = 0
    for p in net.parameters():
        n = int(np.prod(p.shape))
        total += n
        if getattr(p, "trainable", True):
            trainable += n

    w1, w2, w3 = 28, 24, 14
    line = "-" * (w1 + w2 + w3 + 4)
    out = [line,
           f" {'Layer (type)':<{w1}} {'Output Shape':<{w2}} {'Param #':>{w3}}",
           "=" * (w1 + w2 + w3 + 4)]
    for name, shape, n in rows:
        out.append(f" {name:<{w1}} {shape:<{w2}} {n:>{w3},}")
    out.append("=" * (w1 + w2 + w3 + 4))
    out.append(f"Total params: {total:,}")
    out.append(f"Trainable params: {trainable:,}")
    out.append(f"Non-trainable params: {total - trainable:,}")
    param_bytes = sum(int(np.prod(p.shape)) * _dtype_size(str(p.dtype))
                      for p in net.parameters())
    out.append(f"Params size (MB): {param_bytes / 1024 / 1024:.2f}")
    out.append(line)
    print("\n".join(out))
    return {"total_params": total, "trainable_params": trainable}
