"""hapi high-level Model API.

Reference: python/paddle/hapi/model.py — ``Model`` (``:1054``) wrapping a
Layer with prepare/fit/evaluate/predict/save/load, driven by the callbacks
in callbacks.py; distributed data parallel handled inside
(prepare_distributed_context, model.py:225).

TPU-native: the dygraph path runs the eager tape; under a hybrid topology
the network is wrapped in paddle_tpu.DataParallel so inputs shard over the
dp mesh axis and GSPMD emits the gradient reductions.
"""
from __future__ import annotations

import os
import pickle
import warnings
from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.tensor import Tensor
from ..io.dataloader import DataLoader
from ..io.dataset import Dataset
from ..metric import Metric
from ..nn.layer import Layer
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Model:
    """hapi/model.py Model:1054 analog."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._prepared = False
        self.stop_training = False
        self._step_guard = None
        self._ckpt_include_optimizer = True
        self._jit = False
        self._train_step = None
        self._fused_n_in = None
        self._pending_eager_grads = False
        self._resume_replay = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=False, plan=None):
        """``jit=True`` compiles forward + backward + optimizer update into
        ONE fused XLA executable (``paddle_tpu.jit.TrainStep``) with the
        param/master/opt-state buffers DONATED by default — XLA updates
        them in place, halving steady-state update HBM. The DF006 alias
        audit is consulted first; any finding downgrades to non-donating.
        ``train_batch`` falls back to the eager tape whenever the fused
        step can't serve the call (metrics that need forward outputs, an
        armed step guard, gradient accumulation).

        ``plan`` (a ``distributed.mesh.TrainMeshPlan``, from
        ``MeshRuntime.train_plan``) compiles the fused step SPMD: state
        lives sharded per the plan, the runtime SH/MEM gate vets the
        program before compile, and per-axis collective bytes feed the
        roofline gap attribution. Requires ``jit=True``."""
        if plan is not None and not jit:
            raise ValueError("prepare(plan=...) requires jit=True — the "
                             "mesh plan shards the FUSED train step")
        self._mesh_plan = plan
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer)
                                     or callable(loss)):
            raise TypeError("loss must be a Layer or a callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu.metric.Metric")
        self._amp_configs = amp_configs
        self._jit = bool(jit)
        self._train_step = None
        self._fused_n_in = None
        self._prepared = True

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    # -- single-batch entry points -------------------------------------------
    def _forward(self, inputs):
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in _to_list(inputs)]
        outputs = self.network(*ins)
        return _to_list(outputs)

    def _compute_loss(self, outputs, labels):
        labels = [y if isinstance(y, Tensor) else Tensor(np.asarray(y))
                  for y in _to_list(labels)]
        loss = self._loss(*(outputs + labels))
        return loss, labels

    def train_batch(self, inputs, labels=None, update=True):
        """model.py train_batch analog: one eager forward/backward/(step).

        With a step guard enabled (enable_step_guard), a non-finite loss
        SKIPS backward + optimizer.step (NaN gradients would poison every
        weight), counts the skip, and after K consecutive bad steps rolls
        the model back to the last valid checkpoint."""
        import time as _time
        assert self._prepared, "call prepare() first"
        self.network.train()
        from ..resilience.chaos import fault_point
        spec = fault_point("train.step")
        if spec is None and self._can_fuse(update):
            return self._train_batch_fused(inputs, labels)
        t0 = _time.perf_counter()
        outputs = self._forward(inputs)
        loss, labels_t = self._compute_loss(outputs, labels)
        if spec is not None and spec.kind == "nan_grad":
            # the injected divergence: a NaN loss whose backward would
            # produce NaN gradients — exactly what the guard exists for
            loss = loss * float("nan")
        if self._step_guard is not None \
                and self._step_guard.observe(float(loss)) != "ok":
            # skip: no backward, no step; drop any accumulated gradients
            # (they may predate the rollback's restored weights)
            if self._optimizer is not None:
                self._optimizer.clear_grad()
            metrics = self._update_metrics(outputs, labels_t)
            self._observe_train_step(_time.perf_counter() - t0, inputs)
            return self._wrap_loss(loss, metrics)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
            self._pending_eager_grads = False
        else:
            self._pending_eager_grads = True
        metrics = self._update_metrics(outputs, labels_t)
        self._observe_train_step(_time.perf_counter() - t0, inputs)
        return self._wrap_loss(loss, metrics)

    # -- fused (compiled) train step ------------------------------------------
    def _can_fuse(self, update):
        """The fused TrainStep serves only the plain steady-state step:
        no metrics (they need eager forward outputs), no armed step guard
        (it inspects the loss BEFORE backward), no gradient accumulation
        in flight (the fused step fuses backward+update, it cannot add to
        an eager tape's accumulated grads)."""
        return (self._jit and update and not self._metrics
                and self._step_guard is None
                and not self._pending_eager_grads
                and self._loss is not None and self._optimizer is not None)

    def _ensure_train_step(self, n_in):
        if self._train_step is not None and self._fused_n_in == n_in:
            return self._train_step
        from .. import jit as jit_mod
        from ..perf.compile_cache import donation_safe
        donate, findings = donation_safe()
        if not donate:
            warnings.warn(
                f"DF006 alias audit reported {len(findings)} finding(s); "
                "the fused train step will NOT donate param/opt-state "
                "buffers (donation with a wrong alias declaration corrupts "
                "memory on hardware)")
        network, loss = self.network, self._loss

        def loss_fn(*batch):
            outputs = _to_list(network(*batch[:n_in]))
            return loss(*(outputs + list(batch[n_in:])))

        amp = self._amp_configs if isinstance(self._amp_configs, dict) \
            else None
        self._fused_n_in = n_in
        self._train_step = jit_mod.TrainStep(
            loss_fn, self._optimizer, amp=amp, donate=donate,
            mesh_plan=getattr(self, "_mesh_plan", None),
            opprof_label="hapi.train_step")
        return self._train_step

    def _train_batch_fused(self, inputs, labels):
        import time as _time
        t0 = _time.perf_counter()
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in _to_list(inputs)]
        lbls = [y if isinstance(y, Tensor) else Tensor(np.asarray(y))
                for y in _to_list(labels)]
        step = self._ensure_train_step(len(ins))
        args = ins + lbls
        if self._resume_replay:
            # TrainStep's discovery pass doubles as a REAL eager step, and
            # the eager optimizer update is not bitwise-identical to the
            # fused XLA one (different reassociation). An uninterrupted run
            # takes that eager step at step 1; a resumed run would take it
            # at the first post-restore step, forking the trajectory by an
            # ulp. Replay instead: snapshot restored state, let the
            # discovery build+compile, roll the state back (re-placed onto
            # the compiled step's shardings), and run the SAME batch through
            # the compiled path — every post-restore step is then the exact
            # executable the uninterrupted run used.
            self._resume_replay = False
            if not step._cache:
                snap = self._replay_snapshot()
                step(*args)  # discovery + compile; its update is discarded
                self._replay_rollback(snap)
        loss = step(*args)
        self._observe_train_step(_time.perf_counter() - t0, inputs)
        return self._wrap_loss(loss, [])

    def _replay_snapshot(self):
        """Everything the discovery pass mutates: live model tensors,
        optimizer accumulators/masters/step count, and the RNG key."""
        from ..core import random as _random
        opt = self._optimizer
        return {
            "tensors": [(t, t._data, t._grad)
                        for t in self.network._state_dict_raw().values()],
            "accs": {name: dict(store)
                     for name, store in opt._accumulators.items()},
            "masters": dict(opt._master_weights),
            "step_count": opt._step_count,
            "rng": _random.default_generator().get_state(),
        }

    @staticmethod
    def _place_like(old, cur):
        """Re-commit a snapshot array onto the sharding its slot now has
        (the build placed state onto the mesh plan; the compiled step's
        in_shardings reject anything else). device_put is bitwise."""
        import jax
        if old is cur or not isinstance(cur, jax.Array) \
                or not isinstance(old, jax.Array) \
                or getattr(cur, "sharding", None) is None \
                or old.shape != cur.shape:
            return old
        return jax.device_put(old, cur.sharding)

    def _replay_rollback(self, snap):
        from ..core import random as _random
        opt = self._optimizer
        for t, data, grad in snap["tensors"]:
            t._data = self._place_like(data, t._data)
            t._grad = grad
        for name, store in snap["accs"].items():
            cur = opt._accumulators.setdefault(name, {})
            for pid, arr in store.items():
                cur[pid] = self._place_like(arr, cur.get(pid, arr))
        for pid, arr in snap["masters"].items():
            opt._master_weights[pid] = self._place_like(
                arr, opt._master_weights.get(pid, arr))
        opt._step_count = snap["step_count"]
        _random.default_generator().set_state(snap["rng"])

    # -- resilience ----------------------------------------------------------
    def _checkpoint_state(self):
        """The ONE state-dict shape save_checkpoint and the rollback
        restore share (live tensors: restore fills them in place)."""
        sd = {"model": self.network.state_dict()}
        if self._ckpt_include_optimizer and self._optimizer is not None:
            sd["opt"] = self._optimizer.state_dict()
        return sd

    def save_checkpoint(self, manager, step: int, blocking: bool = True):
        """Publish model (+ optimizer) state through a resilience
        CheckpointManager (atomic, checksummed, retained)."""
        return manager.save(self._checkpoint_state(), step,
                            blocking=blocking)

    def resume_from(self, manager, runtime=None):
        """Restore the newest VALID checkpoint into the live model (and
        optimizer) and return its step, or None when the root holds no
        restorable step. Works with both manager flavors; for a
        ``ShardedCheckpointManager`` the restore is elastic — the
        checkpoint re-places under ``runtime`` (default: the prepared
        mesh plan's runtime), whatever mesh it was saved on. Optimizer
        state is pushed back through ``set_state_dict`` because
        ``Optimizer.state_dict()`` hands out fresh wrappers — filling
        those in place would not reach the live accumulators."""
        opt = self._optimizer
        if opt is not None and self._ckpt_include_optimizer:
            # a freshly-built optimizer creates accumulators lazily on
            # its first step; materialize them NOW (and the fp32 masters
            # multi_precision will want) so the checkpoint's moment/
            # master keys have live targets to restore into
            import jax.numpy as jnp
            for p in opt._parameter_list:
                opt._create_accumulators_for(p)
                if opt._multi_precision and p.dtype != jnp.float32:
                    opt._master_weight(p)
        sd = self._checkpoint_state()
        if runtime is None:
            runtime = getattr(getattr(self, "_mesh_plan", None),
                              "runtime", None)
        step = manager.restore_latest(sd, runtime=runtime)
        if step is not None and self._optimizer is not None \
                and "opt" in sd:
            self._optimizer.set_state_dict(sd["opt"])
        if step is not None:
            # the next fused train_batch must not let the discovery pass's
            # eager update touch the restored state (see _train_batch_fused)
            self._resume_replay = True
        return step

    def enable_step_guard(self, rollback_after: Optional[int] = None,
                          checkpoint_manager=None,
                          include_optimizer: bool = True):
        """Arm the non-finite-loss policy on train_batch: skip + count
        every bad step; with `checkpoint_manager` (and `rollback_after`
        = K), the K-th CONSECUTIVE bad step restores the newest valid
        checkpoint saved via save_checkpoint. Returns the StepGuard (its
        ``skipped`` / ``rollbacks`` counters are the test surface)."""
        from ..resilience.recovery import StepGuard
        self._ckpt_include_optimizer = include_optimizer
        restore_fn = None
        if checkpoint_manager is not None:
            def restore_fn():
                return checkpoint_manager.restore_latest(
                    self._checkpoint_state())
        self._step_guard = StepGuard(rollback_after=rollback_after,
                                     restore_fn=restore_fn)
        return self._step_guard

    def disable_step_guard(self):
        self._step_guard = None

    def _observe_train_step(self, dt, inputs):
        """Feed the telemetry registry: step latency, throughput, MFU."""
        from ..observability.metrics import get_registry
        reg = get_registry()
        reg.counter("train_steps_total", "hapi train_batch calls").inc()
        reg.histogram("train_step_seconds",
                      "hapi train_batch wall time").observe(dt)
        ins = _to_list(inputs)
        shapes = tuple(tuple(getattr(x, "shape", None)
                             or np.asarray(x).shape) for x in ins)
        tokens = int(np.prod(shapes[0])) if shapes and shapes[0] else 0
        if tokens and dt > 0:
            reg.gauge("train_tokens_per_sec",
                      "input elements consumed per second by "
                      "train_batch").set(tokens / dt)
        fwd = self._fwd_flops_estimate(shapes)
        if fwd and dt > 0:
            from ..utils.flops import peak_device_flops
            # train ≈ 3× forward (fwd + ~2× bwd), the usual MFU convention
            mfu = 3.0 * fwd / (dt * peak_device_flops())
            reg.gauge("train_mfu",
                      "model FLOPs utilization of the train step").set(
                          mfu)
            # join against ROOFLINE.json: publishes roofline.mfu_gap and
            # the per-phase gap attribution (no-op without the file)
            from ..observability import roofline_attr
            comm_by_axis = None
            mp = getattr(self, "_mesh_plan", None)
            if mp is not None:
                comm_by_axis = mp.collective_bytes_by_axis() or None
                if comm_by_axis:
                    axis_bytes = reg.counter(
                        "collective.axis_bytes_total",
                        "analytic per-step collective bytes of the "
                        "compiled SPMD train step, by mesh axis",
                        labelnames=("axis",))
                    for ax, nb in comm_by_axis.items():
                        axis_bytes.labels(axis=ax).inc(nb)
            roofline_attr.observe_train_step(
                dt, observed_mfu=mfu, tokens=tokens or None,
                params=self._param_count_estimate(),
                comm_bytes_by_axis=comm_by_axis)

    def _param_count_estimate(self) -> Optional[int]:
        """Cached trainable-parameter count (roofline config matching)."""
        n = getattr(self, "_param_count", None)
        if n is None:
            try:
                n = sum(int(np.prod(p.shape))
                        for p in self.network.parameters())
            except Exception:
                n = 0
            self._param_count = n
        return n or None

    def _fwd_flops_estimate(self, shapes):
        """Per-input-shape forward-FLOPs estimate via utils.flops; 0 when
        the hook walker can't drive this net (e.g. int-id inputs)."""
        cache = getattr(self, "_flops_cache", None)
        if cache is None:
            cache = self._flops_cache = {}
        if shapes not in cache:
            try:
                from ..utils.flops import flops as _flops
                cache[shapes] = _flops(self.network,
                                       [list(s) for s in shapes])
            except Exception:
                cache[shapes] = 0
        return cache[shapes]

    def eval_batch(self, inputs, labels=None):
        assert self._prepared, "call prepare() first"
        self.network.eval()
        from ..autograd import no_grad
        with no_grad():
            outputs = self._forward(inputs)
            if self._loss is not None and labels is not None:
                loss, labels_t = self._compute_loss(outputs, labels)
            else:
                loss, labels_t = None, [
                    y if isinstance(y, Tensor) else Tensor(np.asarray(y))
                    for y in _to_list(labels)]
        metrics = self._update_metrics(outputs, labels_t)
        return self._wrap_loss(loss, metrics)

    def predict_batch(self, inputs):
        self.network.eval()
        from ..autograd import no_grad
        with no_grad():
            outputs = self._forward(inputs)
        return [_np(o) for o in outputs]

    def _update_metrics(self, outputs, labels):
        vals = []
        for m in self._metrics:
            computed = m.compute(*(outputs + labels))
            if not isinstance(computed, (list, tuple)):
                computed = [computed]
            vals.append(m.update(*computed))
        return vals

    def _wrap_loss(self, loss, metrics):
        loss_np = [float(loss)] if loss is not None else []
        if self._metrics:
            return loss_np, metrics
        return loss_np

    # -- loops ----------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers,
                     drop_last=False, prefetch=False):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset) or hasattr(data, "__getitem__"):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last,
                              prefetch_to_device=prefetch)
        return data  # any iterable of batches

    def _split_batch(self, batch, has_labels=True):
        """Split a loader batch into (inputs, labels) by declared arity."""
        batch = _to_list(batch)
        if self._labels:
            n_lbl = len(self._labels)
        elif self._loss is not None:
            n_lbl = 1
        else:
            n_lbl = 0
        if not has_labels and len(batch) <= n_lbl:
            # predict path with an unlabeled dataset: the whole batch is input
            return batch, []
        n_in = len(self._inputs) or max(len(batch) - n_lbl, 1)
        ins, lbls = batch[:n_in], batch[n_in:]
        return ins, lbls if has_labels else []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None,
            prefetch_to_device=True, checkpoint=None, checkpoint_freq=1,
            resume=True):
        """model.py fit analog.

        ``prefetch_to_device`` (default on) double-buffers host-to-device
        transfers for loaders fit constructs itself: batch N+1 lands on
        device while step N runs. Pass a pre-built DataLoader to control
        prefetching yourself.

        ``checkpoint`` (a resilience ``CheckpointManager`` or
        ``ShardedCheckpointManager``) turns on periodic checkpointing:
        every ``checkpoint_freq`` global steps the model (+ optimizer)
        state publishes asynchronously (at most one save in flight; the
        next save joins the previous, so a failed publish surfaces as a
        crash whose restart falls back to the last committed step), and
        a final blocking save captures the end state. With ``resume``
        (default) fit first restores the newest valid step — elastically,
        under the prepared mesh plan's runtime — and fast-forwards the
        loader past the batches that step already consumed, so an
        interrupted run continues the SAME trajectory."""
        assert self._prepared, "call prepare() first"
        start_step = 0
        if checkpoint is not None and resume:
            restored = self.resume_from(checkpoint)
            if restored is not None:
                start_step = int(restored)
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last=drop_last,
                                   prefetch=prefetch_to_device)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                batch_size=batch_size, steps=steps,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=self._metrics_name())
        self.stop_training = False
        cbks.on_train_begin({})
        iters_done = start_step
        to_skip = start_step
        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch, {})
            for m in self._metrics:
                m.reset()
            pending_grads = False
            for step, batch in enumerate(loader):
                if to_skip > 0:
                    # resume fast-forward: these batches trained before
                    # the restored checkpoint was taken
                    to_skip -= 1
                    continue
                cbks.on_train_batch_begin(step, {})
                ins, lbls = self._split_batch(batch)
                update = ((step + 1) % accumulate_grad_batches == 0)
                res = self.train_batch(ins, lbls, update=update)
                pending_grads = not update
                logs = self._merge_logs(res)
                cbks.on_train_batch_end(step, logs)
                iters_done += 1
                if checkpoint is not None \
                        and iters_done % checkpoint_freq == 0:
                    checkpoint.wait()      # join the previous async save
                    self.save_checkpoint(checkpoint, iters_done,
                                         blocking=False)
                if num_iters is not None and iters_done >= num_iters:
                    self.stop_training = True
                if self.stop_training:
                    break
            if pending_grads:
                # flush the accumulation tail so gradients never leak into
                # the next epoch's window (works for len-less loaders too)
                self._optimizer.step()
                self._optimizer.clear_grad()
                self._pending_eager_grads = False
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self._run_eval(eval_loader, cbks)
            if self.stop_training:
                break
        if checkpoint is not None:
            checkpoint.wait()
            if iters_done > start_step \
                    and (iters_done % checkpoint_freq != 0
                         or checkpoint.latest_step() != iters_done):
                self.save_checkpoint(checkpoint, iters_done,
                                     blocking=True)
        cbks.on_train_end(logs)

    def _run_eval(self, loader, cbks, num_iters=None):
        for m in self._metrics:
            m.reset()
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks.on_eval_begin({"steps": steps})
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step, {})
            ins, lbls = self._split_batch(batch)
            res = self.eval_batch(ins, lbls)
            logs = self._merge_logs(res)
            cbks.on_eval_batch_end(step, logs)
            if num_iters is not None and step + 1 >= num_iters:
                break
        final = self._finalize_logs(logs)
        cbks.on_eval_end(final)
        return final

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        """model.py evaluate analog: returns {'loss': [...], metric: value}."""
        assert self._prepared, "call prepare() first"
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                log_freq=log_freq, verbose=verbose,
                                metrics=self._metrics_name(), mode="eval")
        return self._run_eval(loader, cbks, num_iters=num_iters)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        """model.py predict analog: list (per output) of per-batch arrays,
        or stacked along batch when stack_outputs=True."""
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose, mode="predict")
        cbks.on_predict_begin({})
        outputs: Optional[List[list]] = None
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step, {})
            ins, _ = self._split_batch(batch, has_labels=False)
            outs = self.predict_batch(ins)
            if outputs is None:
                outputs = [[] for _ in outs]
            for slot, o in zip(outputs, outs):
                slot.append(o)
            cbks.on_predict_batch_end(step, {})
        cbks.on_predict_end({})
        if outputs is None:
            return []
        if stack_outputs:
            return [np.concatenate(slot, axis=0) for slot in outputs]
        return outputs

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            names.extend(_to_list(m.name()))
        return names

    def _merge_logs(self, res):
        logs = {}
        if self._metrics:
            loss_np, _ = res
        else:
            loss_np = res
        if loss_np:
            logs["loss"] = loss_np[0] if len(loss_np) == 1 else loss_np
        for m in self._metrics:
            names = _to_list(m.name())
            vals = _to_list(m.accumulate())
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def _finalize_logs(self, logs):
        return dict(logs)

    # -- persistence -----------------------------------------------------------
    def save(self, path: str, training: bool = True):
        """model.py save analog: <path>.pdparams (+ .pdopt). training=False
        exports the inference program via paddle_tpu.jit.save."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if not training:
            from .. import jit
            spec = self._inputs or None
            jit.save(self.network, path, input_spec=spec)
            return
        from ..framework.io import save as fw_save
        fw_save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            fw_save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False,
             reset_optimizer: bool = False):
        """model.py load analog."""
        from ..framework.io import load as fw_load
        params_path = path + ".pdparams"
        if not os.path.exists(params_path) and os.path.exists(
                path + ".pdiparams"):
            params_path = path + ".pdiparams"  # jit.save inference layout
        params = fw_load(params_path)
        state = self.network.state_dict()
        if skip_mismatch:
            matched = {}
            for k, v in params.items():
                if k in state and tuple(state[k].shape) == tuple(
                        np.asarray(v._data if isinstance(v, Tensor) else v)
                        .shape):
                    matched[k] = v
                else:
                    warnings.warn(f"skip loading {k} (mismatch)")
            params = matched
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(fw_load(opt_path))

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        if input_size is None and self._inputs:
            input_size = [tuple(s.shape) for s in self._inputs]
        return summary(self.network, input_size, dtypes=dtype)


__all__ = ["Model"]
