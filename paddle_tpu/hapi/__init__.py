"""High-level training API (paddle.hapi analog): Model.fit/evaluate/predict,
callbacks, and paddle.summary."""
from __future__ import annotations

from . import callbacks
from .callbacks import (Callback, EarlyStopping, LRScheduler, ModelCheckpoint,
                        ProgBarLogger)
from .model import Model
from .model_summary import summary

__all__ = ["Model", "summary", "callbacks", "Callback", "ProgBarLogger",
           "ModelCheckpoint", "LRScheduler", "EarlyStopping"]
