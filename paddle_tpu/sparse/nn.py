"""paddle.sparse.nn analog (activation layers over sparse values)."""
from __future__ import annotations


class ReLU:
    def __call__(self, x):
        from . import relu
        return relu(x)


class Softmax:
    """Row-wise softmax over the stored values of a 2-D sparse tensor."""

    def __init__(self, axis=-1):
        if axis not in (-1, 1):
            raise NotImplementedError(
                "sparse.nn.Softmax supports the last axis only (2-D row-"
                f"wise); got axis={axis}")
        self.axis = axis

    def __call__(self, x):
        import numpy as np
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        from . import SparseTensor
        idx = np.asarray(x._bcoo.indices)
        vals = np.asarray(x._bcoo.data, dtype=np.float64)
        rows = idx[:, 0]
        out = np.empty_like(vals)
        for r in np.unique(rows):
            m = rows == r
            v = vals[m]
            e = np.exp(v - v.max())
            out[m] = e / e.sum()
        return SparseTensor(jsparse.BCOO(
            (jnp.asarray(out.astype(np.float32)), x._bcoo.indices),
            shape=x.shape), x._fmt)


__all__ = ["ReLU", "Softmax"]
