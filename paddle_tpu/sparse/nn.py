"""paddle.sparse.nn analog (activation layers over sparse values)."""
from __future__ import annotations


class ReLU:
    def __call__(self, x):
        from . import relu
        return relu(x)


class Softmax:
    """Row-wise softmax over the stored values of a 2-D sparse tensor."""

    def __init__(self, axis=-1):
        if axis not in (-1, 1):
            raise NotImplementedError(
                "sparse.nn.Softmax supports the last axis only (2-D row-"
                f"wise); got axis={axis}")
        self.axis = axis

    def __call__(self, x):
        import numpy as np
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        from . import SparseTensor
        idx = np.asarray(x._bcoo.indices)
        vals = np.asarray(x._bcoo.data, dtype=np.float64)
        rows = idx[:, 0]
        out = np.empty_like(vals)
        for r in np.unique(rows):
            m = rows == r
            v = vals[m]
            e = np.exp(v - v.max())
            out[m] = e / e.sum()
        return SparseTensor(jsparse.BCOO(
            (jnp.asarray(out.astype(np.float32)), x._bcoo.indices),
            shape=x.shape), x._fmt)


__all__ = ["ReLU", "Softmax"]


class ReLU6:
    def __call__(self, x):
        from . import _unary
        import jax.numpy as _j
        return _unary(lambda v: _j.clip(v, 0, 6))(x)


class LeakyReLU:
    def __init__(self, negative_slope=0.01):
        self.slope = negative_slope

    def __call__(self, x):
        from . import _unary
        import jax.numpy as _j
        return _unary(lambda v: _j.where(v > 0, v, self.slope * v))(x)


class BatchNorm:
    """sparse.nn.BatchNorm: normalizes the stored values channel-wise (the
    reference normalizes nnz values of an NDHWC/NHWC sparse tensor)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        import jax.numpy as _j
        self.num_features = num_features
        self.eps = epsilon
        self.weight = _j.ones(num_features)
        self.bias = _j.zeros(num_features)

    def __call__(self, x):
        import jax.numpy as _j

        from . import _dense_to_sparse
        from ..core.tensor import Tensor
        dense = x._bcoo.todense()            # channels-last [..., C]
        active = _j.any(dense != 0, axis=-1)
        n_act = _j.maximum(active.sum(), 1)
        # statistics over ACTIVE sites only (the reference normalizes nnz
        # values, not the implicit zeros)
        mask = active[..., None]
        mean = _j.sum(_j.where(mask, dense, 0.0),
                      axis=tuple(range(dense.ndim - 1))) / n_act
        var = _j.sum(_j.where(mask, (dense - mean) ** 2, 0.0),
                     axis=tuple(range(dense.ndim - 1))) / n_act
        out = (dense - mean) / _j.sqrt(var + self.eps)
        out = out * self.weight + self.bias
        out = _j.where(mask, out, 0.0)
        return _dense_to_sparse(Tensor(out), x._fmt)


SyncBatchNorm = BatchNorm


class _SparseConvNd:
    """Submanifold / standard sparse conv via densify -> conv -> re-sparsify
    (the reference's gather-GEMM kernels; on TPU the dense conv IS the MXU
    path, and XLA prunes zero blocks)."""

    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 weight_attr=None, bias_attr=None, data_format=None):
        import numpy as _np

        from ..core.tensor import Parameter
        k = ((kernel_size,) * nd if isinstance(kernel_size, int)
             else tuple(kernel_size))
        scale = 1.0 / max(1, in_channels * int(_np.prod(k))) ** 0.5
        rng = _np.random.RandomState(0)
        self.weight = Parameter(
            (rng.randn(out_channels, in_channels // groups, *k) * scale)
            .astype("float32"))
        self.bias = Parameter(_np.zeros(out_channels, "float32"))
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.nd = nd
        self.subm = subm

    def __call__(self, x):
        import jax.numpy as _j

        from . import _dense_to_sparse
        from ..core.tensor import Tensor
        from ..nn import functional as F
        dense = Tensor(x._bcoo.todense())
        # channels-last sparse layout -> NC... for the conv
        perm = [0, self.nd + 1] + list(range(1, self.nd + 1))
        nchw = dense.transpose(perm)
        conv = F.conv2d if self.nd == 2 else F.conv3d
        out = conv(nchw, self.weight, self.bias, self.stride, self.padding,
                   self.dilation, self.groups)
        back = [0] + list(range(2, self.nd + 2)) + [1]
        out = out.transpose(back)
        if self.subm:
            # submanifold: keep only the input's active sites
            mask = Tensor(_j.any(x._bcoo.todense() != 0, axis=-1,
                                 keepdims=True).astype(_j.float32))
            return _dense_to_sparse(out * mask, "coo")
        return _dense_to_sparse(out, "coo")


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, False)


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, False)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC",
                 key=None):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, True)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 key=None):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, True)


class MaxPool3D:
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def __call__(self, x):
        from . import _dense_to_sparse
        from ..core.tensor import Tensor
        from ..nn import functional as F
        dense = Tensor(x._bcoo.todense())
        nchw = dense.transpose([0, 4, 1, 2, 3])
        out = F.max_pool3d(nchw, self.kernel_size, self.stride, self.padding)
        return _dense_to_sparse(out.transpose([0, 2, 3, 4, 1]), "coo")


__all__ += ["ReLU6", "LeakyReLU", "BatchNorm", "SyncBatchNorm", "Conv2D",
            "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D"]
