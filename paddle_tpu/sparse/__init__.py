"""paddle.sparse analog.

Reference: python/paddle/sparse (SparseCooTensor/SparseCsrTensor creation,
to_dense/to_sparse conversions, sparse matmul/add/mul, unary op family;
C++ kernels under phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse BCOO — XLA lowers sparse
contractions to gather/scatter + dense dot segments, which is the right
trade on an MXU machine (the reference's cuSPARSE role).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from . import nn  # noqa: F401  (sparse.nn.ReLU etc.)


class SparseTensor:
    """Wrapper over a BCOO array with the reference's surface."""

    def __init__(self, bcoo, fmt="coo"):
        self._bcoo = bcoo
        self._fmt = fmt

    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._bcoo.indices, -1, -2))

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return self._fmt == "coo"

    def is_sparse_csr(self):
        return self._fmt == "csr"

    # crs accessors (csr-format views)
    def crows(self) -> Tensor:
        n_rows = self.shape[0]
        rows = np.asarray(self._bcoo.indices)[:, 0]
        counts = np.bincount(rows, minlength=n_rows)
        return Tensor(np.concatenate([[0], np.cumsum(counts)])
                      .astype(np.int64))

    def cols(self) -> Tensor:
        return Tensor(np.asarray(self._bcoo.indices)[:, 1].astype(np.int64))

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"format={self._fmt})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseTensor:
    """paddle.sparse.sparse_coo_tensor analog: indices [ndim, nnz]."""
    idx = np.asarray(indices._data if isinstance(indices, Tensor)
                     else indices)
    val = np.asarray(values._data if isinstance(values, Tensor) else values)
    if dtype is not None:
        val = val.astype(str(dtype).replace("paddle.", ""))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((jnp.asarray(val), jnp.asarray(idx.T)),
                        shape=tuple(shape))
    return SparseTensor(bcoo, "coo")


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseTensor:
    """paddle.sparse.sparse_csr_tensor analog (stored as BCOO internally)."""
    crows_np = np.asarray(crows._data if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(cols._data if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = np.stack([rows, cols_np])
    t = sparse_coo_tensor(idx, values, shape, dtype)
    t._fmt = "csr"
    return t


def _dense_to_sparse(x: Tensor, fmt="coo") -> SparseTensor:
    bcoo = jsparse.BCOO.fromdense(x._data if isinstance(x, Tensor)
                                  else jnp.asarray(x))
    return SparseTensor(bcoo, fmt)


def to_sparse_coo(x, sparse_dim=None):
    return _dense_to_sparse(x, "coo")


def to_sparse_csr(x):
    return _dense_to_sparse(x, "csr")


def _unwrap(x):
    if isinstance(x, SparseTensor):
        return x._bcoo
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def matmul(x, y):
    """sparse @ dense (or sparse @ sparse -> dense result)."""
    a, b = _unwrap(x), _unwrap(y)
    out = a @ b
    if isinstance(out, jsparse.BCOO):
        return SparseTensor(out)
    return Tensor(out)


def masked_matmul(x, y, mask: SparseTensor):
    """Dense@dense evaluated only at mask's nonzero positions (SDDMM)."""
    a, b = _unwrap(x), _unwrap(y)
    idx = mask._bcoo.indices  # [nnz, 2]
    rows = a[idx[:, 0], :]
    cols = b[:, idx[:, 1]].T
    vals = jnp.sum(rows * cols, axis=-1)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask.shape), "coo")


def add(x, y):
    a, b = _unwrap(x), _unwrap(y)
    out = a + b
    if isinstance(out, jsparse.BCOO):
        return SparseTensor(out)
    return Tensor(out)


def multiply(x, y):
    if isinstance(x, SparseTensor) and not isinstance(y, SparseTensor):
        # elementwise scale of stored values
        y_arr = _unwrap(y)
        vals = x._bcoo.data * (y_arr if jnp.ndim(y_arr) == 0 else
                               y_arr[tuple(x._bcoo.indices.T)])
        return SparseTensor(jsparse.BCOO((vals, x._bcoo.indices),
                                         shape=x.shape), x._fmt)
    return add(x, 0) if y is None else Tensor(_unwrap(x) * _unwrap(y))


def _unary(fn):
    def op(x: SparseTensor) -> SparseTensor:
        vals = fn(x._bcoo.data)
        return SparseTensor(jsparse.BCOO((vals, x._bcoo.indices),
                                         shape=x.shape), x._fmt)
    return op


abs = _unary(jnp.abs)
sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
neg = _unary(jnp.negative)
relu = _unary(jax.nn.relu)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


__all__ = ["SparseTensor", "sparse_coo_tensor", "sparse_csr_tensor",
           "to_sparse_coo", "to_sparse_csr", "matmul", "masked_matmul",
           "add", "multiply", "abs", "sin", "tan", "asin", "atan", "sinh",
           "tanh", "asinh", "atanh", "sqrt", "square", "log1p", "expm1",
           "neg", "relu", "is_same_shape", "nn"]


# -- remaining paddle.sparse surface (pow/cast/transpose/reshape/reductions/
#    inplace-value math; ref python/paddle/sparse/unary.py, binary.py,
#    multiary.py) --------------------------------------------------------

def _unary_named(fn):
    def op(x, *args):
        vals = fn(x._bcoo.data, *args)
        return SparseTensor(jsparse.BCOO((vals, x._bcoo.indices),
                                         shape=x.shape), x._fmt)
    return op


pow = _unary_named(lambda v, e: jnp.power(v, e))
deg2rad = _unary_named(jnp.radians)
rad2deg = _unary_named(jnp.degrees)
isnan = _unary_named(jnp.isnan)


def cast(x, index_dtype=None, value_dtype=None):
    from ..core import dtype as dtype_mod
    vals = x._bcoo.data
    idx = x._bcoo.indices
    if value_dtype is not None:
        vals = vals.astype(dtype_mod.to_jax_dtype(value_dtype))
    if index_dtype is not None:
        idx = idx.astype(dtype_mod.to_jax_dtype(index_dtype))
    return SparseTensor(jsparse.BCOO((vals, idx), shape=x.shape), x._fmt)


def subtract(x, y):
    a, b = _unwrap(x), _unwrap(y)
    out = a - b
    if isinstance(out, jsparse.BCOO):
        return SparseTensor(out)
    return Tensor(out)


def divide(x, y):
    """sparse / sparse with identical sparsity, or sparse / dense scalar."""
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor(jsparse.BCOO(
            (x._bcoo.data / y._bcoo.data, x._bcoo.indices),
            shape=x.shape), x._fmt)
    y_arr = _unwrap(y)
    vals = x._bcoo.data / (y_arr if jnp.ndim(y_arr) == 0
                           else y_arr[tuple(x._bcoo.indices.T)])
    return SparseTensor(jsparse.BCOO((vals, x._bcoo.indices),
                                     shape=x.shape), x._fmt)


def mv(x, vec):
    """sparse matrix @ dense vector."""
    return Tensor(_unwrap(x) @ _unwrap(vec))


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta*input + alpha*(x @ y) (ref sparse.addmm)."""
    prod = _unwrap(x) @ _unwrap(y)
    if isinstance(prod, jsparse.BCOO):
        prod = prod.todense()
    base = _unwrap(input)
    if isinstance(base, jsparse.BCOO):
        base = base.todense()
    return Tensor(beta * base + alpha * prod)


def transpose(x, perm):
    return SparseTensor(x._bcoo.transpose(tuple(perm)), x._fmt)


def reshape(x, shape):
    return SparseTensor(x._bcoo.reshape(tuple(int(s) for s in shape)),
                        x._fmt)


def sum(x, axis=None, dtype=None, keepdim=False):
    dense = x._bcoo.todense()
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..core import dtype as dtype_mod
        out = out.astype(dtype_mod.to_jax_dtype(dtype))
    return Tensor(out)


def coalesce(x):
    """Merge duplicate indices (ref sparse.coalesce)."""
    return SparseTensor(x._bcoo.sum_duplicates(), x._fmt)


def slice(x, axes, starts, ends):
    dense = x._bcoo.todense()
    out = dense
    for ax, st, en in zip(axes, starts, ends):
        size = out.shape[ax]
        st = st + size if st < 0 else st
        en = en + size if en < 0 else en
        out = jax.lax.slice_in_dim(out, st, en, axis=ax)
    return _dense_to_sparse(Tensor(out), x._fmt)


def pca_lowrank(x, q=None, center=True, niter=2):
    """Randomized PCA (ref sparse.pca_lowrank / torch.pca_lowrank)."""
    a = _unwrap(x)
    if isinstance(a, jsparse.BCOO):
        a = a.todense()
    import builtins
    m, n = a.shape[-2:]
    if q is None:
        q = builtins.min(6, m, n)
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    from ..core import random as random_mod
    key = random_mod.default_generator().next_key()
    omega = jax.random.normal(key, (n, q), a.dtype)
    y = a @ omega
    for _ in range(niter):
        y = a @ (a.T @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = qmat.T @ a
    u_small, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = qmat @ u_small
    return Tensor(u), Tensor(s), Tensor(vt.T)


__all__ += ["pow", "cast", "subtract", "divide", "mv", "addmm", "transpose",
            "reshape", "sum", "coalesce", "slice", "pca_lowrank", "deg2rad",
            "rad2deg", "isnan"]
