"""Discrete distributions.

Reference: python/paddle/distribution/{bernoulli,categorical,geometric,
multinomial,poisson,binomial}.py.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..ops.registry import dispatch
from .distribution import Distribution, ExponentialFamily, _shape, _t


class Bernoulli(ExponentialFamily):
    """bernoulli.py analog (probs)."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        def _impl(p):
            return p * (1 - p)
        return dispatch(_impl, (self.probs,), {}, op_name="bernoulli_var")

    def sample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(p):
            return jax.random.bernoulli(
                key, jnp.broadcast_to(p, out_shape)).astype(p.dtype)

        return dispatch(_impl, (self.probs,), {},
                        op_name="bernoulli_sample").detach()

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (paddle's rsample w/ temperature)."""
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(p):
            logits = jnp.log(p) - jnp.log1p(-p)
            u = jax.random.uniform(key, out_shape, dtype=p.dtype,
                                   minval=1e-7, maxval=1 - 1e-7)
            lg = jnp.log(u) - jnp.log1p(-u)
            return jax.nn.sigmoid((logits + lg) / temperature)

        return dispatch(_impl, (self.probs,), {},
                        op_name="bernoulli_rsample")

    def log_prob(self, value):
        def _impl(v, p):
            eps = 1e-8
            return v * jnp.log(p + eps) + (1 - v) * jnp.log1p(-p + eps)
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="bernoulli_log_prob")

    def entropy(self):
        def _impl(p):
            eps = 1e-8
            return -(p * jnp.log(p + eps) + (1 - p) * jnp.log1p(-p + eps))
        return dispatch(_impl, (self.probs,), {},
                        op_name="bernoulli_entropy")

    def cdf(self, value):
        def _impl(v, p):
            return jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - p, 1.0))
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="bernoulli_cdf")

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Categorical(Distribution):
    """categorical.py analog (logits; paddle's Categorical takes logits that
    are unnormalized log-probabilities OR positive weights)."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        shp = tuple(self.logits.shape)
        super().__init__(shp[:-1])
        self._num_events = shp[-1]

    @property
    def probs_tensor(self):
        def _impl(l):
            return jax.nn.softmax(l, axis=-1)
        return dispatch(_impl, (self.logits,), {}, op_name="categorical_probs")

    def sample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(l):
            return jax.random.categorical(
                key, jnp.broadcast_to(l, out_shape + (l.shape[-1],)), axis=-1)

        return dispatch(_impl, (self.logits,), {},
                        op_name="categorical_sample").detach()

    def log_prob(self, value):
        def _impl(v, l):
            logp = jax.nn.log_softmax(l, axis=-1)
            v = v.astype(jnp.int32)
            # broadcast sample dims of v against the batch dims of logits
            tgt = jnp.broadcast_shapes(v.shape, logp.shape[:-1])
            logp_b = jnp.broadcast_to(logp, tgt + logp.shape[-1:])
            v_b = jnp.broadcast_to(v, tgt)
            return jnp.take_along_axis(logp_b, v_b[..., None], axis=-1)[..., 0]
        return dispatch(_impl, (_t(value, dtype="int64"), self.logits), {},
                        op_name="categorical_log_prob")

    def probs(self, value):
        lp = self.log_prob(value)
        return dispatch(jnp.exp, (lp,), {}, op_name="categorical_prob")

    def entropy(self):
        def _impl(l):
            logp = jax.nn.log_softmax(l, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return dispatch(_impl, (self.logits,), {},
                        op_name="categorical_entropy")

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Geometric(Distribution):
    """geometric.py analog (probs; support {0, 1, 2, ...})."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        def _impl(p):
            return (1 - p) / p
        return dispatch(_impl, (self.probs,), {}, op_name="geometric_mean")

    @property
    def variance(self):
        def _impl(p):
            return (1 - p) / jnp.square(p)
        return dispatch(_impl, (self.probs,), {}, op_name="geometric_var")

    def sample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(p):
            u = jax.random.uniform(key, out_shape, dtype=p.dtype,
                                   minval=jnp.finfo(p.dtype).tiny)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        return dispatch(_impl, (self.probs,), {},
                        op_name="geometric_sample").detach()

    def log_prob(self, value):
        def _impl(v, p):
            return v * jnp.log1p(-p) + jnp.log(p)
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="geometric_log_prob")

    def entropy(self):
        def _impl(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p
        return dispatch(_impl, (self.probs,), {},
                        op_name="geometric_entropy")

    def cdf(self, value):
        def _impl(v, p):
            return 1 - jnp.power(1 - p, jnp.floor(v) + 1)
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="geometric_cdf")


class Multinomial(Distribution):
    """multinomial.py analog (total_count + probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shp = tuple(self.probs.shape)
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        n = self.total_count

        def _impl(p):
            return n * p
        return dispatch(_impl, (self.probs,), {}, op_name="multinomial_mean")

    @property
    def variance(self):
        n = self.total_count

        def _impl(p):
            return n * p * (1 - p)
        return dispatch(_impl, (self.probs,), {}, op_name="multinomial_var")

    def sample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        n = self.total_count
        out_batch = shape + self.batch_shape
        k = self.event_shape[0]

        def _impl(p):
            logits = jnp.log(jnp.broadcast_to(p, out_batch + (k,)))
            draws = jax.random.categorical(key, logits[..., None, :],
                                           axis=-1,
                                           shape=out_batch + (n,))
            return jnp.sum(jax.nn.one_hot(draws, k, dtype=p.dtype), axis=-2)

        return dispatch(_impl, (self.probs,), {},
                        op_name="multinomial_sample").detach()

    def log_prob(self, value):
        n = self.total_count

        def _impl(v, p):
            logp = jnp.log(p / jnp.sum(p, axis=-1, keepdims=True))
            coeff = (jax.scipy.special.gammaln(jnp.asarray(n + 1.0))
                     - jnp.sum(jax.scipy.special.gammaln(v + 1.0), axis=-1))
            return coeff + jnp.sum(v * logp, axis=-1)
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="multinomial_log_prob")

    def entropy(self):
        """Exact entropy has no closed form; paddle uses the sum of the
        binomial marginal entropies bound — we use a 2nd-order Stirling
        approximation of E[-log P(X)]."""
        n = self.total_count

        def _impl(p):
            # 0.5*log(2 pi e n p (1-p)) per component, Gaussian approx
            return 0.5 * jnp.sum(
                jnp.log(2 * math.pi * math.e * n * p * (1 - p) + 1e-8),
                axis=-1)
        return dispatch(_impl, (self.probs,), {},
                        op_name="multinomial_entropy")


class Poisson(ExponentialFamily):
    """poisson.py analog (rate)."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(r):
            return jax.random.poisson(
                key, jnp.broadcast_to(r, out_shape)).astype(r.dtype)

        return dispatch(_impl, (self.rate,), {},
                        op_name="poisson_sample").detach()

    def log_prob(self, value):
        def _impl(v, r):
            return (v * jnp.log(r) - r
                    - jax.scipy.special.gammaln(v + 1.0))
        return dispatch(_impl, (_t(value), self.rate), {},
                        op_name="poisson_log_prob")

    def entropy(self):
        """Series approximation (matches paddle's approach for large rate)."""
        def _impl(r):
            return (0.5 * jnp.log(2 * math.pi * math.e * r)
                    - 1 / (12 * r) - 1 / (24 * jnp.square(r)))
        return dispatch(_impl, (self.rate,), {}, op_name="poisson_entropy")


class Binomial(Distribution):
    """binomial.py analog (total_count + probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        n = self.total_count

        def _impl(p):
            return n * p
        return dispatch(_impl, (self.probs,), {}, op_name="binomial_mean")

    @property
    def variance(self):
        n = self.total_count

        def _impl(p):
            return n * p * (1 - p)
        return dispatch(_impl, (self.probs,), {}, op_name="binomial_var")

    def sample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        n = self.total_count
        out_shape = shape + self.batch_shape

        def _impl(p):
            u = jax.random.uniform(key, (n,) + out_shape, dtype=p.dtype)
            return jnp.sum((u < p).astype(p.dtype), axis=0)

        return dispatch(_impl, (self.probs,), {},
                        op_name="binomial_sample").detach()

    def log_prob(self, value):
        n = self.total_count

        def _impl(v, p):
            lg = jax.scipy.special.gammaln
            coeff = lg(jnp.asarray(n + 1.0)) - lg(v + 1) - lg(n - v + 1)
            return coeff + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="binomial_log_prob")

    def entropy(self):
        n = self.total_count

        def _impl(p):
            return 0.5 * jnp.log(2 * math.pi * math.e * n * p * (1 - p)
                                 + 1e-8)
        return dispatch(_impl, (self.probs,), {}, op_name="binomial_entropy")


class ContinuousBernoulli(ExponentialFamily):
    """continuous_bernoulli.py analog (Loaiza-Ganem & Cunningham 2019):
    support (0, 1), density C(p) * p^x * (1-p)^(1-x) with normalizer
    C(p) = 2*atanh(1-2p) / (1-2p) (p != 0.5)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _log_norm(self, p):
        # stable around p=0.5 via the taylor expansion the paper uses
        lo, hi = self._lims
        safe = jnp.clip(p, 1e-6, 1 - 1e-6)
        x = 1 - 2 * safe
        direct = jnp.log(2 * jnp.arctanh(x) / jnp.where(
            jnp.abs(x) < 1e-12, 1.0, x))
        taylor = jnp.log(2.0) + 4.0 / 3.0 * x ** 2 + 104.0 / 45.0 * x ** 4
        return jnp.where((safe < lo) | (safe > hi), direct, taylor)

    @property
    def mean(self):
        def _impl(p):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            x = 1 - 2 * safe
            direct = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(x))
            lo, hi = self._lims
            return jnp.where((safe < lo) | (safe > hi), direct, 0.5)
        return dispatch(_impl, (self.probs,), {}, op_name="cb_mean")

    @property
    def variance(self):
        def _impl(p):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            x = 1 - 2 * safe
            m = jnp.where(jnp.abs(x) > 1e-3,
                          safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(x)),
                          0.5)
            direct = safe * (safe - 1) / (1 - 2 * safe) ** 2 \
                + 1 / (2 * jnp.arctanh(x)) ** 2
            return jnp.where(jnp.abs(x) > 1e-3, direct, 1.0 / 12.0)
        return dispatch(_impl, (self.probs,), {}, op_name="cb_var")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(p):
            u = jax.random.uniform(key, out_shape, dtype=p.dtype,
                                   minval=1e-6, maxval=1 - 1e-6)
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            # inverse cdf: x = log1p(u*((1-p)/p)^... ) stable form
            mid = jnp.abs(safe - 0.5) < 1e-4
            ratio = jnp.log1p(-safe) - jnp.log(safe)
            icdf = (jnp.log1p(u * jnp.expm1(-ratio)) + 0.0) / (-ratio)
            return jnp.where(mid, u, icdf)

        return dispatch(_impl, (self.probs,), {}, op_name="cb_rsample")

    def log_prob(self, value):
        def _impl(v, p):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            return (v * jnp.log(safe) + (1 - v) * jnp.log1p(-safe)
                    + self._log_norm(safe))
        return dispatch(_impl, (_t(value), self.probs), {},
                        op_name="cb_log_prob")

    def entropy(self):
        def _impl(p):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            x = 1 - 2 * safe
            m = jnp.where(jnp.abs(x) > 1e-3,
                          safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(x)),
                          0.5)
            return -(m * jnp.log(safe) + (1 - m) * jnp.log1p(-safe)
                     + self._log_norm(safe))
        return dispatch(_impl, (self.probs,), {}, op_name="cb_entropy")
