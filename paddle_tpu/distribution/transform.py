"""Bijective transforms for TransformedDistribution.

Reference: python/paddle/distribution/transform.py (Transform base with
forward/inverse/forward_log_det_jacobian and the stock transforms).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import dispatch
from .distribution import _t


class Transform:
    """transform.py Transform analog."""

    def forward(self, x):
        return dispatch(self._forward, (_t(x),), {},
                        op_name=f"{type(self).__name__}_fwd")

    def inverse(self, y):
        return dispatch(self._inverse, (_t(y),), {},
                        op_name=f"{type(self).__name__}_inv")

    def forward_log_det_jacobian(self, x):
        return dispatch(self._fldj, (_t(x),), {},
                        op_name=f"{type(self).__name__}_fldj")

    def inverse_log_det_jacobian(self, y):
        def _impl(v):
            return -self._fldj(self._inverse(v))
        return dispatch(_impl, (_t(y),), {},
                        op_name=f"{type(self).__name__}_ildj")

    def __call__(self, x):
        return self.forward(x)

    # subclass hooks (pure jnp)
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _forward(self, x):
        return self.loc._data + self.scale._data * x

    def _inverse(self, y):
        return (y - self.loc._data) / self.scale._data

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale._data)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def _forward(self, x):
        return jnp.power(x, self.power._data)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power._data)

    def _fldj(self, x):
        p = self.power._data
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh(x)^2), numerically stable
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    """Non-bijective |x|; inverse picks the positive branch (as reference)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    """Maps unconstrained vectors to the simplex (not bijective; inverse is
    log, as in the reference)."""

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("SoftmaxTransform has no scalar ldj")


class StickBreakingTransform(Transform):
    """R^{K-1} -> simplex interior via stick breaking."""

    def _forward(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,), z.dtype)],
                               axis=-1)
        one_minus = jnp.concatenate(
            [jnp.ones(z.shape[:-1] + (1,), z.dtype), 1 - z], axis=-1)
        return zpad * jnp.cumprod(one_minus, axis=-1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        rem = 1 - jnp.cumsum(y_crop, axis=-1)
        offset = y_crop.shape[-1] - jnp.arange(y_crop.shape[-1],
                                               dtype=y.dtype)
        z = y_crop / jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), rem[..., :-1]], axis=-1)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        rem_log = jnp.cumsum(jnp.log1p(-z), axis=-1)
        shifted = jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,), x.dtype), rem_log[..., :-1]],
            axis=-1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + shifted, axis=-1)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        return jnp.zeros(x.shape[:x.ndim - len(self.in_event_shape)],
                         x.dtype)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = None
        for t in self.transforms:
            l = t._fldj(x)
            total = l if total is None else total + l
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    """Sums the log-det over reinterpreted trailing dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.k = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        l = self.base._fldj(x)
        return jnp.sum(l, axis=tuple(range(-self.k, 0)))


class StackTransform(Transform):
    """Applies a list of transforms along a stacked axis."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fn_name, x):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p.squeeze(self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _fldj(self, x):
        return self._map("_fldj", x)
