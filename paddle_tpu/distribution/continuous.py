"""Continuous distributions.

Reference: python/paddle/distribution/{normal,uniform,beta,gamma,dirichlet,
exponential,laplace,gumbel,lognormal,cauchy,student_t,multivariate_normal}.py.
Each method compiles to one fused XLA op via the registry dispatch.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..ops.registry import dispatch
from .distribution import Distribution, ExponentialFamily, _shape, _t

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


def _bshape(*ts):
    return tuple(np.broadcast_shapes(*[tuple(t.shape) for t in ts]))


class Normal(ExponentialFamily):
    """normal.py Normal analog (loc/scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return dispatch(jnp.square, (self.scale,), {}, op_name="normal_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(loc, scale):
            eps = jax.random.normal(key, out_shape, dtype=loc.dtype)
            return loc + scale * eps

        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="normal_rsample")

    def log_prob(self, value):
        def _impl(v, loc, scale):
            return (-0.5 * jnp.square((v - loc) / scale)
                    - jnp.log(scale) - _HALF_LOG_2PI)

        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="normal_log_prob")

    def entropy(self):
        def _impl(loc, scale):
            return jnp.broadcast_to(0.5 + _HALF_LOG_2PI + jnp.log(scale),
                                    jnp.broadcast_shapes(loc.shape,
                                                         scale.shape))

        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="normal_entropy")

    def cdf(self, value):
        def _impl(v, loc, scale):
            return 0.5 * (1 + jax.scipy.special.erf(
                (v - loc) / (scale * math.sqrt(2.0))))

        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="normal_cdf")

    def icdf(self, value):
        def _impl(p, loc, scale):
            return loc + scale * math.sqrt(2.0) * jax.scipy.special.erfinv(
                2 * p - 1)

        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="normal_icdf")

    def probs(self, value):
        return self.prob(value)

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class LogNormal(Normal):
    """lognormal.py analog: exp(Normal(loc, scale))."""

    @property
    def mean(self):
        def _impl(loc, scale):
            return jnp.exp(loc + 0.5 * jnp.square(scale))
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="lognormal_mean")

    @property
    def variance(self):
        def _impl(loc, scale):
            s2 = jnp.square(scale)
            return (jnp.exp(s2) - 1) * jnp.exp(2 * loc + s2)
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="lognormal_var")

    def rsample(self, shape=()):
        z = Normal.rsample(self, shape)
        return dispatch(jnp.exp, (z,), {}, op_name="lognormal_rsample")

    def log_prob(self, value):
        def _impl(v, loc, scale):
            lv = jnp.log(v)
            return (-0.5 * jnp.square((lv - loc) / scale)
                    - jnp.log(scale) - _HALF_LOG_2PI - lv)
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="lognormal_log_prob")

    def entropy(self):
        def _impl(loc, scale):
            return jnp.broadcast_to(
                0.5 + _HALF_LOG_2PI + jnp.log(scale) + loc,
                jnp.broadcast_shapes(loc.shape, scale.shape))
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="lognormal_entropy")

    def cdf(self, value):
        def _impl(v, loc, scale):
            return 0.5 * (1 + jax.scipy.special.erf(
                (jnp.log(v) - loc) / (scale * math.sqrt(2.0))))
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="lognormal_cdf")


class Uniform(Distribution):
    """uniform.py Uniform analog (low/high)."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(_bshape(self.low, self.high))

    @property
    def mean(self):
        def _impl(lo, hi):
            return (lo + hi) / 2
        return dispatch(_impl, (self.low, self.high), {},
                        op_name="uniform_mean")

    @property
    def variance(self):
        def _impl(lo, hi):
            return jnp.square(hi - lo) / 12
        return dispatch(_impl, (self.low, self.high), {},
                        op_name="uniform_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(lo, hi):
            u = jax.random.uniform(key, out_shape, dtype=lo.dtype)
            return lo + (hi - lo) * u

        return dispatch(_impl, (self.low, self.high), {},
                        op_name="uniform_rsample")

    def log_prob(self, value):
        def _impl(v, lo, hi):
            inside = jnp.logical_and(v >= lo, v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return dispatch(_impl, (_t(value), self.low, self.high), {},
                        op_name="uniform_log_prob")

    def entropy(self):
        def _impl(lo, hi):
            return jnp.log(hi - lo)
        return dispatch(_impl, (self.low, self.high), {},
                        op_name="uniform_entropy")

    def cdf(self, value):
        def _impl(v, lo, hi):
            return jnp.clip((v - lo) / (hi - lo), 0.0, 1.0)
        return dispatch(_impl, (_t(value), self.low, self.high), {},
                        op_name="uniform_cdf")


class Exponential(ExponentialFamily):
    """exponential.py analog (rate)."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return dispatch(jnp.reciprocal, (self.rate,), {}, op_name="exp_mean")

    @property
    def variance(self):
        def _impl(r):
            return 1.0 / jnp.square(r)
        return dispatch(_impl, (self.rate,), {}, op_name="exp_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(r):
            u = jax.random.uniform(key, out_shape, dtype=r.dtype,
                                   minval=jnp.finfo(r.dtype).tiny)
            return -jnp.log(u) / r

        return dispatch(_impl, (self.rate,), {}, op_name="exp_rsample")

    def log_prob(self, value):
        def _impl(v, r):
            return jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf)
        return dispatch(_impl, (_t(value), self.rate), {},
                        op_name="exp_log_prob")

    def entropy(self):
        def _impl(r):
            return 1.0 - jnp.log(r)
        return dispatch(_impl, (self.rate,), {}, op_name="exp_entropy")

    def cdf(self, value):
        def _impl(v, r):
            return jnp.where(v >= 0, 1 - jnp.exp(-r * v), 0.0)
        return dispatch(_impl, (_t(value), self.rate), {}, op_name="exp_cdf")


class Laplace(Distribution):
    """laplace.py analog (loc/scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def _impl(s):
            return 2.0 * jnp.square(s)
        return dispatch(_impl, (self.scale,), {}, op_name="laplace_var")

    @property
    def stddev(self):
        def _impl(s):
            return math.sqrt(2.0) * s
        return dispatch(_impl, (self.scale,), {}, op_name="laplace_std")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(loc, scale):
            u = jax.random.uniform(key, out_shape, dtype=loc.dtype,
                                   minval=-0.5 + 1e-7, maxval=0.5)
            return loc - scale * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u))

        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="laplace_rsample")

    def log_prob(self, value):
        def _impl(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="laplace_log_prob")

    def entropy(self):
        def _impl(loc, scale):
            return jnp.broadcast_to(1 + jnp.log(2 * scale),
                                    jnp.broadcast_shapes(loc.shape,
                                                         scale.shape))
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="laplace_entropy")

    def cdf(self, value):
        def _impl(v, loc, scale):
            z = (v - loc) / scale
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="laplace_cdf")

    def icdf(self, value):
        def _impl(p, loc, scale):
            a = p - 0.5
            return loc - scale * jnp.sign(a) * jnp.log1p(-2 * jnp.abs(a))
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="laplace_icdf")


class Gumbel(Distribution):
    """gumbel.py analog (loc/scale)."""

    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        def _impl(loc, scale):
            return loc + self._EULER * scale
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="gumbel_mean")

    @property
    def variance(self):
        def _impl(s):
            return (math.pi ** 2 / 6.0) * jnp.square(s)
        return dispatch(_impl, (self.scale,), {}, op_name="gumbel_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(loc, scale):
            g = jax.random.gumbel(key, out_shape, dtype=loc.dtype)
            return loc + scale * g

        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="gumbel_rsample")

    def log_prob(self, value):
        def _impl(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="gumbel_log_prob")

    def entropy(self):
        def _impl(loc, scale):
            return jnp.broadcast_to(jnp.log(scale) + 1 + self._EULER,
                                    jnp.broadcast_shapes(loc.shape,
                                                         scale.shape))
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="gumbel_entropy")

    def cdf(self, value):
        def _impl(v, loc, scale):
            return jnp.exp(-jnp.exp(-(v - loc) / scale))
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="gumbel_cdf")


class Cauchy(Distribution):
    """cauchy.py analog (loc/scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(_bshape(self.loc, self.scale))

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(loc, scale):
            u = jax.random.uniform(key, out_shape, dtype=loc.dtype,
                                   minval=1e-7, maxval=1.0 - 1e-7)
            return loc + scale * jnp.tan(math.pi * (u - 0.5))

        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="cauchy_rsample")

    def log_prob(self, value):
        def _impl(v, loc, scale):
            z = (v - loc) / scale
            return -math.log(math.pi) - jnp.log(scale) - jnp.log1p(
                jnp.square(z))
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="cauchy_log_prob")

    def entropy(self):
        def _impl(loc, scale):
            return jnp.broadcast_to(math.log(4 * math.pi) + jnp.log(scale),
                                    jnp.broadcast_shapes(loc.shape,
                                                         scale.shape))
        return dispatch(_impl, (self.loc, self.scale), {},
                        op_name="cauchy_entropy")

    def cdf(self, value):
        def _impl(v, loc, scale):
            return jnp.arctan((v - loc) / scale) / math.pi + 0.5
        return dispatch(_impl, (_t(value), self.loc, self.scale), {},
                        op_name="cauchy_cdf")


class Gamma(ExponentialFamily):
    """gamma.py analog (concentration/rate)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(_bshape(self.concentration, self.rate))

    @property
    def mean(self):
        def _impl(a, r):
            return a / r
        return dispatch(_impl, (self.concentration, self.rate), {},
                        op_name="gamma_mean")

    @property
    def variance(self):
        def _impl(a, r):
            return a / jnp.square(r)
        return dispatch(_impl, (self.concentration, self.rate), {},
                        op_name="gamma_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(a, r):
            g = jax.random.gamma(key, jnp.broadcast_to(a, out_shape),
                                 dtype=a.dtype)
            return g / r

        return dispatch(_impl, (self.concentration, self.rate), {},
                        op_name="gamma_rsample")

    def log_prob(self, value):
        def _impl(v, a, r):
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(a))
        return dispatch(_impl, (_t(value), self.concentration, self.rate), {},
                        op_name="gamma_log_prob")

    def entropy(self):
        def _impl(a, r):
            return (a - jnp.log(r) + jax.scipy.special.gammaln(a)
                    + (1 - a) * jax.scipy.special.digamma(a))
        return dispatch(_impl, (self.concentration, self.rate), {},
                        op_name="gamma_entropy")


class Beta(ExponentialFamily):
    """beta.py analog (alpha/beta)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(_bshape(self.alpha, self.beta))

    @property
    def mean(self):
        def _impl(a, b):
            return a / (a + b)
        return dispatch(_impl, (self.alpha, self.beta), {},
                        op_name="beta_mean")

    @property
    def variance(self):
        def _impl(a, b):
            s = a + b
            return a * b / (jnp.square(s) * (s + 1))
        return dispatch(_impl, (self.alpha, self.beta), {},
                        op_name="beta_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(a, b):
            return jax.random.beta(key, jnp.broadcast_to(a, out_shape),
                                   jnp.broadcast_to(b, out_shape))

        return dispatch(_impl, (self.alpha, self.beta), {},
                        op_name="beta_rsample")

    def log_prob(self, value):
        def _impl(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.gammaln(a)
                       + jax.scipy.special.gammaln(b)
                       - jax.scipy.special.gammaln(a + b)))
        return dispatch(_impl, (_t(value), self.alpha, self.beta), {},
                        op_name="beta_log_prob")

    def entropy(self):
        def _impl(a, b):
            dg = jax.scipy.special.digamma
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return dispatch(_impl, (self.alpha, self.beta), {},
                        op_name="beta_entropy")


class Dirichlet(ExponentialFamily):
    """dirichlet.py analog (concentration)."""

    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shp = tuple(self.concentration.shape)
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        def _impl(a):
            return a / jnp.sum(a, axis=-1, keepdims=True)
        return dispatch(_impl, (self.concentration,), {},
                        op_name="dirichlet_mean")

    @property
    def variance(self):
        def _impl(a):
            a0 = jnp.sum(a, axis=-1, keepdims=True)
            m = a / a0
            return m * (1 - m) / (a0 + 1)
        return dispatch(_impl, (self.concentration,), {},
                        op_name="dirichlet_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape + self.event_shape

        def _impl(a):
            return jax.random.dirichlet(
                key, jnp.broadcast_to(a, out_shape), dtype=a.dtype)

        return dispatch(_impl, (self.concentration,), {},
                        op_name="dirichlet_rsample")

    def log_prob(self, value):
        def _impl(v, a):
            lbeta = (jnp.sum(jax.scipy.special.gammaln(a), axis=-1)
                     - jax.scipy.special.gammaln(jnp.sum(a, axis=-1)))
            return jnp.sum((a - 1) * jnp.log(v), axis=-1) - lbeta
        return dispatch(_impl, (_t(value), self.concentration), {},
                        op_name="dirichlet_log_prob")

    def entropy(self):
        def _impl(a):
            dg = jax.scipy.special.digamma
            k = a.shape[-1]
            a0 = jnp.sum(a, axis=-1)
            lbeta = (jnp.sum(jax.scipy.special.gammaln(a), axis=-1)
                     - jax.scipy.special.gammaln(a0))
            return (lbeta + (a0 - k) * dg(a0)
                    - jnp.sum((a - 1) * dg(a), axis=-1))
        return dispatch(_impl, (self.concentration,), {},
                        op_name="dirichlet_entropy")


class StudentT(Distribution):
    """student_t.py analog (df/loc/scale)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(_bshape(self.df, self.loc, self.scale))

    @property
    def mean(self):
        def _impl(df, loc):
            return jnp.where(df > 1, loc, jnp.nan)
        return dispatch(_impl, (self.df, self.loc), {},
                        op_name="studentt_mean")

    @property
    def variance(self):
        def _impl(df, scale):
            v = jnp.square(scale) * df / (df - 2)
            return jnp.where(df > 2, v,
                             jnp.where(df > 1, jnp.inf, jnp.nan))
        return dispatch(_impl, (self.df, self.scale), {},
                        op_name="studentt_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape

        def _impl(df, loc, scale):
            t = jax.random.t(key, jnp.broadcast_to(df, out_shape),
                             dtype=loc.dtype)
            return loc + scale * t

        return dispatch(_impl, (self.df, self.loc, self.scale), {},
                        op_name="studentt_rsample")

    def log_prob(self, value):
        def _impl(v, df, loc, scale):
            z = (v - loc) / scale
            return (jax.scipy.special.gammaln((df + 1) / 2)
                    - jax.scipy.special.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(scale)
                    - (df + 1) / 2 * jnp.log1p(jnp.square(z) / df))
        return dispatch(_impl, (_t(value), self.df, self.loc, self.scale), {},
                        op_name="studentt_log_prob")

    def entropy(self):
        def _impl(df, scale):
            dg = jax.scipy.special.digamma
            return ((df + 1) / 2 * (dg((df + 1) / 2) - dg(df / 2))
                    + 0.5 * jnp.log(df)
                    + jax.scipy.special.betaln(df / 2, 0.5)
                    + jnp.log(scale))
        return dispatch(_impl, (self.df, self.scale), {},
                        op_name="studentt_entropy")


class MultivariateNormal(Distribution):
    """multivariate_normal.py analog (loc + covariance_matrix)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _t(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError("give exactly one of covariance_matrix / "
                             "scale_tril")
        if covariance_matrix is not None:
            self.covariance_matrix = _t(covariance_matrix)
            self._scale_tril = dispatch(
                jnp.linalg.cholesky, (self.covariance_matrix,), {},
                op_name="mvn_chol")
        else:
            self._scale_tril = _t(scale_tril)

            def _cov(L):
                return L @ jnp.swapaxes(L, -1, -2)
            self.covariance_matrix = dispatch(
                _cov, (self._scale_tril,), {}, op_name="mvn_cov")
        d = tuple(self.loc.shape)[-1]
        batch = tuple(np.broadcast_shapes(
            tuple(self.loc.shape)[:-1],
            tuple(self._scale_tril.shape)[:-2]))
        super().__init__(batch, (d,))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def _impl(cov):
            return jnp.diagonal(cov, axis1=-2, axis2=-1)
        return dispatch(_impl, (self.covariance_matrix,), {},
                        op_name="mvn_var")

    def rsample(self, shape=()):
        shape = _shape(shape)
        key = random_mod.next_key()
        out_shape = shape + self.batch_shape + self.event_shape

        def _impl(loc, L):
            eps = jax.random.normal(key, out_shape, dtype=loc.dtype)
            return loc + jnp.einsum("...ij,...j->...i", L, eps)

        return dispatch(_impl, (self.loc, self._scale_tril), {},
                        op_name="mvn_rsample")

    def log_prob(self, value):
        def _impl(v, loc, L):
            d = loc.shape[-1]
            diff = v - loc
            sol = jax.scipy.linalg.solve_triangular(L, diff[..., None],
                                                    lower=True)[..., 0]
            maha = jnp.sum(jnp.square(sol), axis=-1)
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                             axis=-1)
            return -0.5 * maha - logdet - 0.5 * d * math.log(2 * math.pi)
        return dispatch(_impl, (_t(value), self.loc, self._scale_tril), {},
                        op_name="mvn_log_prob")

    def entropy(self):
        def _impl(L):
            d = L.shape[-1]
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                             axis=-1)
            return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet
        return dispatch(_impl, (self._scale_tril,), {},
                        op_name="mvn_entropy")
