"""paddle.distribution analog.

Reference: python/paddle/distribution/__init__.py. Distributions compute
through the op registry, so log_prob/rsample land on the autograd tape as
single fused XLA ops, and sampling threads the framework RNG (compiled-step
capture tracks the key state).
"""
from __future__ import annotations

from .continuous import (Beta, Cauchy, Dirichlet, Exponential, Gamma, Gumbel,
                         Laplace, LogNormal, MultivariateNormal, Normal,
                         StudentT, Uniform)
from .discrete import (Bernoulli, Binomial, Categorical,
                       ContinuousBernoulli, Geometric,
                       Multinomial, Poisson)
from .distribution import (Distribution, ExponentialFamily, Independent,
                           TransformedDistribution)
from .kl import kl_divergence, register_kl
from .transform import (AbsTransform, AffineTransform, ChainTransform,
                        ExpTransform, IndependentTransform, PowerTransform,
                        ReshapeTransform, SigmoidTransform, SoftmaxTransform,
                        StackTransform, StickBreakingTransform, TanhTransform,
                        Transform)

__all__ = [
    "Distribution", "ExponentialFamily", "Independent",
    "TransformedDistribution",
    "Normal", "Uniform", "Beta", "Gamma", "Dirichlet", "Exponential",
    "Laplace", "Gumbel", "LogNormal", "Cauchy", "StudentT",
    "MultivariateNormal",
    "Bernoulli", "Categorical", "ContinuousBernoulli", "Geometric",
    "Multinomial", "Poisson",
    "Binomial",
    "kl_divergence", "register_kl",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]
