"""KL divergence registry.

Reference: python/paddle/distribution/kl.py — ``kl_divergence(p, q)``
dispatching on a (type(p), type(q)) registry built with ``@register_kl``,
with MRO-aware lookup.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..ops.registry import dispatch
from .continuous import (Beta, Dirichlet, Exponential, Gamma, Gumbel, Laplace,
                         LogNormal, Normal, Uniform)
from .discrete import Bernoulli, Categorical, Geometric, Poisson
from .distribution import Distribution

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """kl.py register_kl analog."""

    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco


def _dispatch_kl(cls_p, cls_q):
    matches = []
    for (p, q), fn in _KL_REGISTRY.items():
        if issubclass(cls_p, p) and issubclass(cls_q, q):
            matches.append((cls_p.__mro__.index(p) + cls_q.__mro__.index(q),
                            fn))
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({cls_p.__name__}, {cls_q.__name__})")
    return min(matches, key=lambda t: t[0])[1]


def kl_divergence(p: Distribution, q: Distribution):
    """kl.py kl_divergence analog."""
    return _dispatch_kl(type(p), type(q))(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def _impl(lp, sp, lq, sq):
        var_ratio = jnp.square(sp / sq)
        t1 = jnp.square((lp - lq) / sq)
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return dispatch(_impl, (p.loc, p.scale, q.loc, q.scale), {},
                    op_name="kl_normal_normal")


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def _impl(plo, phi, qlo, qhi):
        res = jnp.log((qhi - qlo) / (phi - plo))
        return jnp.where(jnp.logical_and(qlo <= plo, phi <= qhi), res,
                         jnp.inf)
    return dispatch(_impl, (p.low, p.high, q.low, q.high), {},
                    op_name="kl_uniform_uniform")


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def _impl(pp, pq):
        eps = 1e-8
        t1 = pp * (jnp.log(pp + eps) - jnp.log(pq + eps))
        t2 = (1 - pp) * (jnp.log1p(-pp + eps) - jnp.log1p(-pq + eps))
        return t1 + t2
    return dispatch(_impl, (p.probs, q.probs), {},
                    op_name="kl_bernoulli_bernoulli")


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def _impl(lp, lq):
        logp = jax.nn.log_softmax(lp, axis=-1)
        logq = jax.nn.log_softmax(lq, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)
    return dispatch(_impl, (p.logits, q.logits), {},
                    op_name="kl_categorical_categorical")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def _impl(pa, pb, qa, qb):
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma

        def lbeta(a, b):
            return lg(a) + lg(b) - lg(a + b)
        return (lbeta(qa, qb) - lbeta(pa, pb)
                + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
                + (qa - pa + qb - pb) * dg(pa + pb))
    return dispatch(_impl, (p.alpha, p.beta, q.alpha, q.beta), {},
                    op_name="kl_beta_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def _impl(pa, qa):
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        pa0 = jnp.sum(pa, axis=-1)
        qa0 = jnp.sum(qa, axis=-1)
        return (lg(pa0) - jnp.sum(lg(pa), axis=-1)
                - lg(qa0) + jnp.sum(lg(qa), axis=-1)
                + jnp.sum((pa - qa) * (dg(pa) - dg(pa0)[..., None]),
                          axis=-1))
    return dispatch(_impl, (p.concentration, q.concentration), {},
                    op_name="kl_dirichlet_dirichlet")


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def _impl(pa, pr, qa, qr):
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        return ((pa - qa) * dg(pa) - lg(pa) + lg(qa)
                + qa * (jnp.log(pr) - jnp.log(qr))
                + pa * (qr / pr - 1))
    return dispatch(_impl, (p.concentration, p.rate, q.concentration,
                            q.rate), {}, op_name="kl_gamma_gamma")


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    def _impl(pr, qr):
        ratio = qr / pr
        return ratio - 1 - jnp.log(ratio)
    return dispatch(_impl, (p.rate, q.rate), {}, op_name="kl_exp_exp")


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def _impl(lp, sp, lq, sq):
        ratio = sp / sq
        d = jnp.abs(lp - lq)
        return (-jnp.log(ratio) + ratio - 1
                + d / sq
                + ratio * jnp.expm1(-d / sp))
    return dispatch(_impl, (p.loc, p.scale, q.loc, q.scale), {},
                    op_name="kl_laplace_laplace")


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    def _impl(pp, pq):
        return (-(1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-pq))
                + jnp.log(pp) - jnp.log(pq))
    return dispatch(_impl, (p.probs, q.probs), {}, op_name="kl_geo_geo")


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    def _impl(pr, qr):
        return pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr
    return dispatch(_impl, (p.rate, q.rate), {},
                    op_name="kl_poisson_poisson")


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    """Closed form for equal-family Gumbel KL (via expectations)."""
    _E = 0.57721566490153286060

    def _impl(lp, sp, lq, sq):
        ratio = sp / sq
        # E_p[(x - lq)/sq] = (lp - lq)/sq + E*sp/sq ; E_p[e^{-(x-lq)/sq}] below
        t = (lp - lq) / sq
        expterm = jnp.exp(-t + jax.scipy.special.gammaln(1 + ratio))
        return (jnp.log(sq) - jnp.log(sp) + _E * (ratio - 1)
                + t + expterm - (1 + _E))
    return dispatch(_impl, (p.loc, p.scale, q.loc, q.scale), {},
                    op_name="kl_gumbel_gumbel")


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p, q)
