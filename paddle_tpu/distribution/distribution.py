"""Distribution base classes.

Reference: python/paddle/distribution/distribution.py (Distribution base with
sample/rsample/log_prob/prob/entropy + batch_shape), exponential_family.py,
independent.py, transformed_distribution.py.

TPU-native: every method body is ONE dispatched op (a fused jnp closure), so
a log_prob or entropy lands on the autograd tape as a single node and XLA
fuses the arithmetic; sampling draws keys from the framework generator so
compiled-step capture tracks RNG state.
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor
from ..ops.registry import dispatch


def _t(x, dtype=None):
    """Coerce arg to Tensor (floating by default)."""
    if isinstance(x, Tensor):
        return x
    arr = np.asarray(x)
    if dtype is None and arr.dtype.kind in "iub":
        arr = arr.astype("float32")
    elif dtype is not None:
        arr = arr.astype(dtype)
    return Tensor(arr)


def _shape(s):
    if s is None:
        return ()
    if isinstance(s, (int, np.integer)):
        return (int(s),)
    return tuple(int(d) for d in s)


class Distribution:
    """distribution.py Distribution analog."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape(batch_shape)
        self._event_shape = _shape(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        v = self.variance
        return dispatch(jnp.sqrt, (v,), {}, op_name="dist_stddev")

    def sample(self, shape=()):
        """Draw (no grad through the sample)."""
        s = self.rsample(shape)
        return s.detach() if hasattr(s, "detach") else s

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        lp = self.log_prob(value)
        return dispatch(jnp.exp, (lp,), {}, op_name="dist_prob")

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return _shape(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")


class ExponentialFamily(Distribution):
    """exponential_family.py analog (marker base; entropy via the Bregman
    identity is specialized per subclass here rather than generically)."""


class Independent(Distribution):
    """independent.py analog: reinterprets trailing batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        b = base.batch_shape
        k = self.reinterpreted_batch_rank
        if k > len(b):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        super().__init__(b[:len(b) - k], b[len(b) - k:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))

        def _impl(a):
            return jnp.sum(a, axis=axes)

        return dispatch(_impl, (lp,), {}, op_name="independent_log_prob")

    def entropy(self):
        ent = self.base.entropy()
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))

        def _impl(a):
            return jnp.sum(a, axis=axes)

        return dispatch(_impl, (ent,), {}, op_name="independent_entropy")


class TransformedDistribution(Distribution):
    """transformed_distribution.py analog: push base samples through a chain
    of bijective transforms; log_prob uses the change-of-variables formula."""

    def __init__(self, base, transforms):
        from .transform import ChainTransform, Transform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        self._chain = (transforms[0] if len(transforms) == 1
                       else ChainTransform(self.transforms))
        super().__init__(base.batch_shape, base.event_shape)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def sample(self, shape=()):
        s = self.rsample(shape)
        return s.detach()

    def log_prob(self, value):
        value = _t(value)
        lp = None
        y = value
        # walk the chain backwards, accumulating -log|det J|
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ladj = t.forward_log_det_jacobian(x)
            lp = ladj if lp is None else dispatch(
                jnp.add, (lp, ladj), {}, op_name="td_ladj_sum")
            y = x
        base_lp = self.base.log_prob(y)

        def _impl(b, l):
            return b - l

        return dispatch(_impl, (base_lp, lp), {}, op_name="td_log_prob")
