"""paddle.hub analog.

Reference: python/paddle/hapi/hub.py — list/help/load over a repo
containing ``hubconf.py``. Offline environment: only ``source='local'``
works; github/gitee sources raise with a clear message.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

MODULE_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise RuntimeError(
            f"hub source '{source}' needs network access, unavailable in "
            f"this environment; use source='local' with a checked-out repo")


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:  # noqa: A001 — paddle.hub.list name
    """Entrypoints exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"{model} not found in {repo_dir}/{MODULE_HUBCONF}")
    return fn.__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"{model} not found in {repo_dir}/{MODULE_HUBCONF}")
    return fn(**kwargs)


__all__ = ["list", "help", "load"]
