"""Shared retry/backoff policy.

One policy object serves every control-plane caller — the launcher's
KVClient, fleet elastic heartbeats, distributed.rpc connection setup,
and checkpoint I/O — instead of each growing its own ad-hoc loop:

  * exponential backoff with multiplicative growth, capped per-attempt;
  * full jitter (a seeded ``random.Random`` so tests replay exactly);
  * a total DEADLINE cap: sleeps are clipped to the remaining budget and
    the policy gives up when the budget is spent, whatever max_attempts
    says;
  * per-attempt telemetry through the observability registry
    (``retry_attempts_total`` / ``retry_giveups_total`` labeled by call
    site).

Retryability is type-driven: ``retryable`` exception classes are retried
unless they also match ``giveup`` (checked first — e.g. HTTPError is a
URLError subclass but a 4xx must not be retried). Injected
``TransientChaosError``s are retryable by default so chaos drills
exercise these loops.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type

from .chaos import TransientChaosError

__all__ = ["RetryPolicy", "RetryGiveUp", "DEFAULT_RETRYABLE"]

DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError, TransientChaosError)


class RetryGiveUp(RuntimeError):
    """Raised when the policy exhausts attempts/deadline. ``last`` holds
    the final underlying exception (also chained as __cause__)."""

    def __init__(self, msg: str, last: BaseException):
        super().__init__(msg)
        self.last = last


@dataclass(frozen=True)
class RetryPolicy:
    """Deterministic-by-seed exponential backoff with deadline cap."""

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5           # fraction of the backoff randomized away
    deadline: Optional[float] = None   # total seconds across all attempts
    retryable: Tuple[Type[BaseException], ...] = DEFAULT_RETRYABLE
    giveup: Tuple[Type[BaseException], ...] = ()
    seed: Optional[int] = None    # None → wall-clock-seeded jitter
    # injectable for tests (field, not global, so policies are reusable)
    sleep_fn: Callable[[float], None] = field(default=time.sleep)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1.0")

    # -- the math (exposed so tests pin it exactly) -------------------------
    def backoff(self, attempt: int) -> float:
        """Deterministic pre-jitter delay after the Nth failure (0-based):
        min(max_delay, base_delay * multiplier**attempt)."""
        return min(self.max_delay,
                   self.base_delay * self.multiplier ** attempt)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Jittered delay: backoff * (1 - jitter * U[0,1))."""
        b = self.backoff(attempt)
        if self.jitter <= 0:
            return b
        return b * (1.0 - self.jitter * rng.random())

    def _is_retryable(self, exc: BaseException) -> bool:
        if self.giveup and isinstance(exc, self.giveup):
            return False
        return isinstance(exc, self.retryable)

    # -- the loop -----------------------------------------------------------
    def call(self, fn: Callable, *args, point: str = "", **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying per the policy. ``point``
        labels the telemetry series (use the caller's seam name)."""
        attempts_c, giveups_c = _retry_metrics()
        label = point or getattr(fn, "__name__", "call")
        rng = random.Random(self.seed)
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempts_c.labels(point=label).inc()
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 — filtered below
                if not self._is_retryable(exc):
                    raise
                attempt += 1
                remaining = (None if self.deadline is None
                             else self.deadline - (time.monotonic() - t0))
                if attempt >= self.max_attempts or \
                        (remaining is not None and remaining <= 0):
                    giveups_c.labels(point=label).inc()
                    raise RetryGiveUp(
                        f"{label}: gave up after {attempt} attempt(s) "
                        f"({type(exc).__name__}: {exc})", exc) from exc
                d = self.delay(attempt - 1, rng)
                if remaining is not None:
                    d = min(d, max(0.0, remaining))
                self.sleep_fn(d)

    def wrap(self, fn: Callable, point: str = "") -> Callable:
        """fn → retrying fn (partial application of ``call``)."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, point=point, **kwargs)
        return wrapped


def _retry_metrics():
    from ..observability.metrics import get_registry
    reg = get_registry()
    return (reg.counter("retry_attempts_total",
                        "calls issued under a retry policy",
                        labelnames=("point",)),
            reg.counter("retry_giveups_total",
                        "retry policies exhausted (deadline or attempts)",
                        labelnames=("point",)))
