"""Elastic mesh-sharded checkpointing: two-phase multi-rank save,
rescale-on-restore.

Reference surface: fleet elastic training's assumption that a job
survives worker loss and resumes on a DIFFERENT worker set. The
round-6 ``CheckpointManager`` publishes a single-process replicated
state_dict atomically; this module extends the same lifecycle to a
mesh-sharded world where no single process holds (or can even address)
the full state.

Protocol — two-phase commit over one shared step directory:

  PHASE 1 (every rank): write the shard chunks THIS rank owns
    (min-process-index replica dedup over ``devices_indices_map``) as
    ``shard-rankNNNNN-PPP.npz`` files with per-chunk crc32, then an
    ``SHARD_OK.rankNNNNN`` ack naming every chunk it wrote. Both land
    through the ``checkpoint.shard_write`` chaos seam, so drills can
    tear a shard file or kill a rank BETWEEN chunk write and ack.
  PHASE 2 (rank 0 only): poll for all ``world_size`` acks; on timeout
    ABORT without publishing (exactly what a rank killed mid-save
    leaves behind — a torn step no restore will ever pick). With every
    ack observed, merge them into ``MANIFEST.json`` (step, mesh axes,
    per-tensor global shape + ShardSpec dims + chunk list/CRCs) and
    drop the ``COMMITTED`` marker — both through the
    ``checkpoint.publish`` seam. COMMITTED is the commit point: the
    base manager's hidden-tmp + rename trick cannot span ranks.

Restore is ELASTIC: ``restore_latest(runtime=...)`` walks steps newest
first, validates manifest <-> acks <-> shard files <-> checksums, and
reassembles each tensor from whatever chunk layout it was SAVED under
via ``MeshRuntime.place_from_shards`` (jax.make_array_from_callback
under the CURRENT mesh) — save on 2x2 ``(fsdp, tensor)``, restore on
1x4, 4x1, or a single device. Placement is exact slicing, so combined
with the mesh runtime's bitwise-exact ZeRO-3 math the continued loss
trajectory is bitwise identical to the uninterrupted run. Every
checkpoint discarded on the way down is a typed ``CheckpointFinding``
(``torn_step`` / ``missing_ack`` / ``checksum_mismatch`` / ...), never
a silent fallback.
"""
from __future__ import annotations

import glob
import json
import os
import pickle
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .checkpoint_manager import (COMMITTED_MARKER, CheckpointManager,
                                 validate_checkpoint, write_committed_marker)
from .retry import RetryPolicy

__all__ = [
    "ShardedCheckpointManager", "MANIFEST_FILE", "ACK_PREFIX",
    "AckTimeout", "validate_sharded_checkpoint", "is_sharded_step",
]

MANIFEST_FILE = "MANIFEST.json"
ACK_PREFIX = "SHARD_OK.rank"
EXTRA_PICKLE = "extra_state.pkl"
MANIFEST_FORMAT = "paddle_tpu.sharded_checkpoint"
MANIFEST_VERSION = 1


class AckTimeout(RuntimeError):
    """Rank 0 gave up waiting for shard acks — the step stays torn
    (never published), which is the protocol working as designed."""


def _ack_name(rank: int) -> str:
    return f"{ACK_PREFIX}{rank:05d}"


def _shard_name(rank: int, part: int) -> str:
    return f"shard-rank{rank:05d}-{part:03d}.npz"


def is_sharded_step(path: str) -> bool:
    """Does this step directory use the sharded (two-phase) layout?"""
    if os.path.exists(os.path.join(path, MANIFEST_FILE)):
        return True
    # ".npz*" also catches the ".tmp" a torn/killed chunk write leaves —
    # that debris is still proof a sharded save started here
    return bool(glob.glob(os.path.join(path, ACK_PREFIX + "*"))
                or glob.glob(os.path.join(path, "shard-rank*.npz*")))


def validate_sharded_checkpoint(path: str) -> Tuple[bool, str]:
    """(ok, reason) for a two-phase step dir: COMMITTED present,
    manifest readable, every ack it names on disk, every chunk's crc32
    matching. A step with shard writes but no manifest is TORN — the
    signature a rank death between shard-write and publish leaves."""
    from ..distributed.checkpoint.metadata import chunk_crc
    if not os.path.isdir(path):
        return False, "not a directory"
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(mpath):
        if is_sharded_step(path):
            return False, ("torn step: shard writes present but no "
                           "MANIFEST.json was published")
        return False, "no MANIFEST.json"
    if not os.path.exists(os.path.join(path, COMMITTED_MARKER)):
        return False, "no COMMITTED marker"
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except Exception as exc:  # noqa: BLE001 — any unreadable byte = invalid
        return False, f"unreadable (MANIFEST.json: {exc})"
    if manifest.get("format") != MANIFEST_FORMAT:
        return False, f"unreadable (format {manifest.get('format')!r})"
    for ack in manifest.get("acks", ()):
        if not os.path.exists(os.path.join(path, ack)):
            return False, f"missing shard ack {ack!r}"
    npz_cache: Dict[str, object] = {}
    try:
        for key, entry in manifest.get("tensors", {}).items():
            for ch in entry.get("chunks", ()):
                fname = ch["file"]
                fpath = os.path.join(path, fname)
                if not os.path.exists(fpath):
                    return False, f"missing shard file {fname!r}"
                if fname not in npz_cache:
                    try:
                        npz_cache[fname] = np.load(fpath)
                    except Exception as exc:  # noqa: BLE001
                        return False, f"unreadable ({fname}: {exc})"
                try:
                    data = npz_cache[fname][ch["cid"]]
                except Exception:  # noqa: BLE001
                    return False, (f"shard file {fname!r} has no chunk "
                                   f"{ch['cid']!r}")
                got = chunk_crc(data)
                if got != int(ch["crc"]):
                    return False, (f"checksum mismatch for {ch['cid']} "
                                   f"({got:#x} != {int(ch['crc']):#x})")
    finally:
        for f in npz_cache.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass
    return True, "ok"


@dataclass
class _Chunk:
    """One owned shard region snapshotted to host (stored-dtype bytes)."""
    key: str
    cid: str
    offset: Tuple[int, ...]
    shape: Tuple[int, ...]
    data: np.ndarray     # raw-bit encoded (bf16/fp8 ride as u16/u8)
    crc: int


class _Snapshot:
    """Host-side capture of one rank's view of the state_dict."""

    def __init__(self):
        self.chunks: List[_Chunk] = []
        self.tensors: Dict[str, dict] = {}
        self.extra: Dict[str, object] = {}
        self.extra_pickle: Dict[str, object] = {}


class _ShardReader:
    """Lazy per-file npz reader for manifest chunks."""

    def __init__(self, path: str):
        self._path = path
        self._files: Dict[str, object] = {}

    def read(self, ch: dict) -> np.ndarray:
        fname = ch["file"]
        if fname not in self._files:
            self._files[fname] = np.load(os.path.join(self._path, fname))
        return self._files[fname][ch["cid"]]

    def close(self):
        for f in self._files.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass


class ShardedCheckpointManager(CheckpointManager):
    """Two-phase, per-rank-sharded checkpoint store over one root.

    Single-process worlds degrade gracefully: rank 0 is the only
    participant, writes its shards, immediately sees its own ack, and
    publishes — the same files a multi-rank save produces, so a
    checkpoint saved by N ranks restores in 1 process and vice versa.
    """

    def __init__(self, root: str, keep_last: int = 3,
                 retry: Optional[RetryPolicy] = None,
                 runtime=None, rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 ack_timeout: float = 60.0, poll_interval: float = 0.05,
                 shard_max_bytes: int = 256 << 20,
                 wait_commit: bool = False):
        super().__init__(root, keep_last=keep_last, retry=retry)
        self.runtime = runtime
        if rank is None or world_size is None:
            jr, jw = _default_rank_world()
            rank = jr if rank is None else rank
            world_size = jw if world_size is None else world_size
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.ack_timeout = float(ack_timeout)
        self.poll_interval = float(poll_interval)
        self.shard_max_bytes = int(shard_max_bytes)
        #: non-rank-0 ranks block until COMMITTED appears (or timeout)
        #: when True — lockstep callers that immediately read back want it
        self.wait_commit = bool(wait_commit)

    # -- save -----------------------------------------------------------------
    def save(self, state_dict: Dict, step: int,
             blocking: bool = True) -> str:
        """Two-phase publish of `state_dict` as step `step`. The
        device->host snapshot happens NOW on the caller's thread (so the
        training step may immediately mutate state); with
        ``blocking=False`` only the file I/O + ack-wait + publish ride
        the background thread (join with ``wait()``, same machinery as
        the base manager — a fault in the async window surfaces there
        while restores keep landing on the previous committed step)."""
        snap = self._snapshot(state_dict)
        final = self._step_dir(step)
        if blocking:
            self._publish_sharded(snap, step, final)
            return final

        def run():
            try:
                self._publish_sharded(snap, step, final)
            except BaseException as exc:  # noqa: BLE001 — wait() re-raises
                self._errors.append(exc)

        t = threading.Thread(target=run, daemon=True,
                             name=f"ckpt-shard-save-{step}")
        t.start()
        self._threads.append(t)
        return final

    def _snapshot(self, state_dict: Dict) -> _Snapshot:
        from ..core.tensor import Tensor
        from ..distributed.checkpoint.metadata import Metadata, chunk_crc
        from ..distributed.checkpoint.save_load import (_flatten,
                                                        encode_stored_array)
        from ..distributed.mesh import spec_of_array, spec_to_json
        snap = _Snapshot()
        for key, value in _flatten(state_dict).items():
            if not isinstance(value, Tensor):
                try:
                    json.dumps(value)
                    snap.extra[key] = value
                except (TypeError, ValueError):
                    snap.extra_pickle[key] = value
                continue
            arr = value._data
            gshape = tuple(int(d) for d in arr.shape)
            snap.tensors[key] = {
                "global_shape": list(gshape),
                "dtype": str(arr.dtype),
                "spec": spec_to_json(spec_of_array(arr, ndim=len(gshape))),
            }
            for offset, data in self._owned_shards(arr, gshape):
                # ascontiguousarray promotes 0-d to (1,); put it back
                stored = encode_stored_array(
                    np.ascontiguousarray(data).reshape(data.shape))
                snap.chunks.append(_Chunk(
                    key=key, cid=Metadata.chunk_id(key, offset),
                    offset=offset, shape=tuple(data.shape),
                    data=stored, crc=chunk_crc(stored)))
        return snap

    def _owned_shards(self, arr, gshape):
        """(offset, host_data) for every shard THIS rank owns: among the
        processes holding a replica of a given offset, the minimum
        process index writes it — each chunk lands exactly once however
        the mesh replicates."""
        from ..distributed.checkpoint.save_load import shard_index_to_offset
        shards = getattr(arr, "addressable_shards", None)
        if shards is None:           # host/numpy value: rank 0 owns it all
            if self.rank == 0:
                yield (0,) * len(gshape), np.asarray(arr)
            return
        owners: Dict[Tuple[int, ...], int] = {}
        try:
            dmap = arr.sharding.devices_indices_map(gshape)
        except Exception:  # noqa: BLE001 — fall back to local-only dedup
            dmap = None
        if dmap:
            for dev, idx in dmap.items():
                off, _ = shard_index_to_offset(idx, gshape)
                p = int(getattr(dev, "process_index", 0))
                if off not in owners or p < owners[off]:
                    owners[off] = p
        seen = set()
        for shard in shards:
            off, _ = shard_index_to_offset(shard.index, gshape)
            if off in seen:
                continue
            seen.add(off)
            if owners.get(off, self.rank) != self.rank:
                continue
            yield off, np.asarray(shard.data)

    def _publish_sharded(self, snap: _Snapshot, step: int, final: str):
        from ..observability.flight import flight_record
        t0 = time.perf_counter()
        save_h, bytes_c = self._sharded_metrics()
        os.makedirs(final, exist_ok=True)
        flight_record("ckpt.save_begin", step=step, rank=self.rank,
                      chunks=len(snap.chunks))

        # PHASE 1: shard chunk files, then the ack naming them
        files: Dict[str, List[dict]] = {}
        for part, group in enumerate(self._partition(snap.chunks)):
            fname = _shard_name(self.rank, part)
            from ..distributed.checkpoint.save_load import pack_npz
            payload = pack_npz({c.cid: c.data for c in group})
            self.retry.call(self._write_file, final, fname, payload,
                            "checkpoint.shard_write",
                            point="checkpoint.shard_write")
            bytes_c.labels(rank=str(self.rank)).inc(len(payload))
            files[fname] = [{"cid": c.cid, "key": c.key,
                             "offset": list(c.offset),
                             "shape": list(c.shape), "crc": c.crc}
                            for c in group]
        if self.rank == 0 and snap.extra_pickle:
            self.retry.call(self._write_file, final, EXTRA_PICKLE,
                            pickle.dumps(snap.extra_pickle),
                            "checkpoint.shard_write",
                            point="checkpoint.shard_write")
        ack = {"rank": self.rank, "step": step, "files": files,
               "tensors": snap.tensors, "extra": snap.extra}
        self.retry.call(self._write_file, final, _ack_name(self.rank),
                        json.dumps(ack).encode(), "checkpoint.shard_write",
                        point="checkpoint.shard_write")
        flight_record("ckpt.shard_ack", step=step, rank=self.rank,
                      files=len(files))

        # PHASE 2: rank 0 merges acks -> manifest -> COMMITTED
        if self.rank == 0:
            acks = self._await_acks(final, step)
            manifest = self._merge_manifest(step, acks,
                                            bool(snap.extra_pickle))
            self.retry.call(
                self._write_file, final, MANIFEST_FILE,
                json.dumps(manifest, indent=1, sort_keys=True).encode(),
                "checkpoint.publish", point="checkpoint.publish")
            write_committed_marker(
                final, step,
                extra={"format": MANIFEST_FORMAT,
                       "world_size": self.world_size},
                chaos_point="checkpoint.publish")
            flight_record("ckpt.commit", step=step,
                          world_size=self.world_size)
            save_h.observe(time.perf_counter() - t0)
            self._apply_retention()
        elif self.wait_commit:
            self._await_committed(final, step)

    @staticmethod
    def _write_file(dirpath: str, fname: str, payload: bytes,
                    chaos_seam: str):
        """Temp + rename through the named chaos seam: a torn write or
        kill leaves at worst a ``.tmp`` no reader trusts (shard files
        are only believed when an ack/manifest names them)."""
        from .chaos import torn_write_bytes
        fpath = os.path.join(dirpath, fname)
        tmp = fpath + ".tmp"
        torn_write_bytes(tmp, payload, point=chaos_seam)
        os.replace(tmp, fpath)

    def _partition(self, chunks: Sequence[_Chunk]) -> List[List[_Chunk]]:
        parts: List[List[_Chunk]] = []
        cur: List[_Chunk] = []
        size = 0
        for c in chunks:
            if cur and size + c.data.nbytes > self.shard_max_bytes:
                parts.append(cur)
                cur, size = [], 0
            cur.append(c)
            size += c.data.nbytes
        if cur:
            parts.append(cur)
        return parts

    def _await_acks(self, final: str, step: int) -> List[dict]:
        from ..observability.flight import flight_record
        deadline = time.monotonic() + self.ack_timeout
        while True:
            names = sorted(os.path.basename(p) for p in glob.glob(
                os.path.join(final, ACK_PREFIX + "*")))
            if len(names) >= self.world_size:
                out = []
                for n in names:
                    with open(os.path.join(final, n),
                              "r", encoding="utf-8") as f:
                        out.append(json.load(f))
                return sorted(out, key=lambda a: a.get("rank", 0))
            if time.monotonic() >= deadline:
                missing = sorted(
                    set(range(self.world_size))
                    - {int(n[len(ACK_PREFIX):]) for n in names})
                flight_record("ckpt.ack_timeout", step=step,
                              missing=",".join(map(str, missing)))
                raise AckTimeout(
                    f"step {step}: gave up after {self.ack_timeout}s "
                    f"waiting for shard acks from rank(s) {missing} — "
                    "step left unpublished (torn)")
            time.sleep(self.poll_interval)

    def _await_committed(self, final: str, step: int):
        deadline = time.monotonic() + self.ack_timeout
        marker = os.path.join(final, COMMITTED_MARKER)
        while not os.path.exists(marker):
            if time.monotonic() >= deadline:
                raise AckTimeout(
                    f"step {step}: rank {self.rank} gave up after "
                    f"{self.ack_timeout}s waiting for COMMITTED")
            time.sleep(self.poll_interval)

    def _merge_manifest(self, step: int, acks: List[dict],
                        has_pickle: bool) -> dict:
        tensors: Dict[str, dict] = {}
        extra: Dict[str, object] = {}
        ack_names = []
        for a in acks:
            ack_names.append(_ack_name(int(a["rank"])))
            for key, meta in a.get("tensors", {}).items():
                tensors.setdefault(key, dict(meta)).setdefault("chunks", [])
            for key, v in a.get("extra", {}).items():
                extra.setdefault(key, v)
            for fname, chunk_metas in a.get("files", {}).items():
                for m in chunk_metas:
                    tensors[m["key"]]["chunks"].append({
                        "file": fname, "cid": m["cid"],
                        "offset": m["offset"], "shape": m["shape"],
                        "crc": m["crc"]})
        return {
            "format": MANIFEST_FORMAT, "version": MANIFEST_VERSION,
            "step": step, "world_size": self.world_size,
            "mesh": dict(self.runtime.axes) if self.runtime is not None
            else None,
            "acks": ack_names,
            "tensors": tensors,
            "extra": extra,
            "extra_pickle": EXTRA_PICKLE if has_pickle else None,
        }

    # -- restore --------------------------------------------------------------
    def validate(self, step: int) -> Tuple[bool, str]:
        path = self._step_dir(step)
        if is_sharded_step(path):
            return validate_sharded_checkpoint(path)
        return validate_checkpoint(path)   # legacy single-process layout

    def _do_restore(self, state_dict: Dict, step: int, runtime=None,
                    specs: Optional[Dict[str, Sequence]] = None) -> None:
        """Elastic load of one VALIDATED step: each tensor reassembles
        from the saved chunk layout under the CURRENT mesh
        (``runtime`` arg > manager's runtime > host assembly). ``specs``
        optionally overrides per-key placement; otherwise a tensor
        already resident on the target mesh keeps its live spec, and
        anything else restores replicated (the next jitted step
        reshards it to the plan's placement — exact slicing either
        way, so the continuation stays bitwise)."""
        path = self._step_dir(step)
        if not is_sharded_step(path):
            return super()._do_restore(state_dict, step)
        import jax

        from ..core.tensor import Tensor
        from ..distributed.checkpoint.save_load import (
            _unflatten_keys, decode_stored_array, np_dtype, overlap_slices)
        from ..distributed.mesh import spec_of_array
        with open(os.path.join(path, MANIFEST_FILE),
                  "r", encoding="utf-8") as f:
            manifest = json.load(f)
        rt = runtime if runtime is not None else self.runtime
        extra = manifest.get("extra", {})
        extra_pk: Dict[str, object] = {}
        if manifest.get("extra_pickle"):
            ppath = os.path.join(path, manifest["extra_pickle"])
            if os.path.exists(ppath):
                with open(ppath, "rb") as f:
                    extra_pk = pickle.load(f)
        reader = _ShardReader(path)
        try:
            for key, (container, leaf) in _unflatten_keys(
                    state_dict).items():
                value = container[leaf]
                if not isinstance(value, Tensor):
                    if key in extra:
                        container[leaf] = extra[key]
                    elif key in extra_pk:
                        container[leaf] = extra_pk[key]
                    continue
                entry = manifest["tensors"].get(key)
                if entry is None:
                    raise KeyError(
                        f"checkpoint step {step} has no tensor {key!r}")
                gshape = tuple(int(d) for d in value._data.shape)
                if gshape != tuple(entry["global_shape"]):
                    raise ValueError(
                        f"{key}: target global shape {gshape} != stored "
                        f"{tuple(entry['global_shape'])}")
                tdtype = np.dtype(value._data.dtype)
                stored_dtype = np_dtype(entry["dtype"])
                chunks = entry["chunks"]

                def read_chunk(i, _chunks=chunks, _sd=stored_dtype,
                               _td=tdtype):
                    data = decode_stored_array(reader.read(_chunks[i]),
                                               _sd)
                    # older shards stored 0-d chunks promoted to (1,);
                    # the manifest shape is authoritative
                    data = data.reshape(tuple(_chunks[i]["shape"]))
                    return data if data.dtype == _td else data.astype(_td)

                spec = None if specs is None else specs.get(key)
                if spec is None and rt is not None:
                    live = value._data
                    if (isinstance(live, jax.Array)
                            and getattr(live, "sharding", None) is not None
                            and set(live.sharding.device_set)
                            == set(rt.mesh.devices.flat)):
                        # mid-training in-place restore: keep the live
                        # placement, assemble per-target-shard only
                        spec = spec_of_array(live, ndim=len(gshape))
                if rt is not None and spec is not None:
                    value._set_data(rt.place_from_shards(
                        gshape, tdtype, spec,
                        [{"offset": ch["offset"], "shape": ch["shape"]}
                         for ch in chunks], read_chunk))
                    continue
                # pre-placement restore (or no runtime): assemble the
                # full tensor on host, single-device — the fused step's
                # place_state commits it to the plan's mesh spec on the
                # next call (an AOT-compiled executable pins its input
                # shardings, so guessing a mesh placement here would be
                # rejected; exact slicing either way keeps the
                # continuation bitwise)
                buf = np.empty(gshape, dtype=tdtype)
                filled = np.zeros(gshape, dtype=bool)
                for i, ch in enumerate(chunks):
                    ov = overlap_slices(
                        (0,) * len(gshape), gshape,
                        tuple(ch["offset"]), tuple(ch["shape"]))
                    if ov is None:
                        continue
                    dst_sl, src_sl = ov
                    buf[dst_sl] = read_chunk(i)[src_sl]
                    filled[dst_sl] = True
                if not filled.all():
                    raise ValueError(
                        f"{key}: stored chunks do not cover the global "
                        f"shape (missing {int((~filled).sum())} elems)")
                value._set_data(jax.device_put(buf))
        finally:
            reader.close()

    # -- telemetry ------------------------------------------------------------
    def _sharded_metrics(self):
        from ..observability.metrics import get_registry
        reg = get_registry()
        return (reg.histogram("checkpoint.save_seconds",
                              "two-phase sharded save wall time "
                              "(snapshot done -> COMMITTED)"),
                reg.counter("checkpoint.bytes_written",
                            "shard-file bytes written, by rank",
                            labelnames=("rank",)))


def _default_rank_world() -> Tuple[int, int]:
    """(rank, world): the live jax distributed identity when initialized
    (it reflects the ACTUAL device world), else the launcher env."""
    try:
        import jax
        if jax.process_count() > 1:
            return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001
        pass
    return (int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0),
            int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1))
