"""paddle_tpu.resilience — fault injection, retry, and recovery.

The resilience layer ties the pieces the distributed stack already has
(watchdog, elastic manager, restart budgets, sharded checkpointing) into
recovery loops that are PROVABLE under injected failures on CPU today:

  * ``chaos``              — deterministic, seed-driven fault injection
    at named points (``checkpoint.write``, ``collective.enter``,
    ``serving.step``, ``kv.request``, ``dataloader.next``,
    ``train.step``), armed via ``PADDLE_CHAOS`` scenario specs.
  * ``retry``              — the shared exponential-backoff/deadline
    policy the KVClient, rpc, elastic heartbeats, and checkpoint I/O use.
  * ``checkpoint_manager`` — crash-safe checkpoint lifecycle: atomic
    publish, per-array checksums, keep-last-N retention, async save,
    and fallback ``restore_latest()``.
  * ``recovery``           — ``StepGuard`` (non-finite-loss skip +
    rollback), typed serving rejections (``Overloaded``,
    ``DeadlineExceeded``), and the serving ``HealthStateMachine``.

Everything reports through ``paddle_tpu.observability``
(``faults_injected_total``, ``recoveries_total``,
``checkpoint_restore_seconds``, ``requests_shed_total``, ...).
"""
from __future__ import annotations

from . import (chaos, checkpoint_manager, recovery, remediator, retry,
               sharded_checkpoint)
from .chaos import (ChaosError, ChaosRegistry, FaultSpec,
                    TransientChaosError, TornWrite, arm_from_env,
                    arm_scenario, disarm, fault_point, get_chaos,
                    parse_scenario, torn_write_bytes)
from .checkpoint_manager import (COMMITTED_MARKER, CheckpointFinding,
                                 CheckpointManager, validate_checkpoint)
from .recovery import (DeadlineExceeded, HealthState, HealthStateMachine,
                       Overloaded, StepGuard)
from .remediator import (ACTION_KINDS, AutoRemediator, DEFAULT_POLICY,
                         FlapGuard, PolicyRule, RemediationAction, Signal,
                         remediate_enabled)
from .retry import DEFAULT_RETRYABLE, RetryGiveUp, RetryPolicy
from .sharded_checkpoint import (AckTimeout, ShardedCheckpointManager,
                                 validate_sharded_checkpoint)

__all__ = [
    "chaos", "retry", "checkpoint_manager", "recovery",
    "sharded_checkpoint", "remediator",
    "AutoRemediator", "RemediationAction", "PolicyRule", "Signal",
    "FlapGuard", "DEFAULT_POLICY", "ACTION_KINDS", "remediate_enabled",
    "ChaosError", "TransientChaosError", "TornWrite", "FaultSpec",
    "ChaosRegistry", "get_chaos", "fault_point", "arm_scenario",
    "arm_from_env", "disarm", "parse_scenario", "torn_write_bytes",
    "RetryPolicy", "RetryGiveUp", "DEFAULT_RETRYABLE",
    "CheckpointManager", "COMMITTED_MARKER", "validate_checkpoint",
    "CheckpointFinding", "ShardedCheckpointManager", "AckTimeout",
    "validate_sharded_checkpoint",
    "StepGuard", "Overloaded", "DeadlineExceeded", "HealthState",
    "HealthStateMachine",
]
