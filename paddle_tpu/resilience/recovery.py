"""Recovery policies at the training and serving seams.

Three small, reusable pieces the subsystems compose:

  * ``StepGuard`` — the hapi train loop's non-finite-loss policy: skip
    the optimizer step (gradients from a NaN/Inf loss are poison), count
    the skip, and after K CONSECUTIVE bad steps optionally roll the model
    back to the last valid checkpoint via a ``CheckpointManager``.
  * ``Overloaded`` / ``DeadlineExceeded`` — the serving batchers' typed
    rejections (queue-depth shedding, per-request deadlines). Typed so a
    fronting layer can map them to 429/504 without string-matching.
  * ``HealthStateMachine`` — STARTING → READY ⇄ DEGRADED → UNREADY, the
    readiness/liveness surface a load balancer polls. DEGRADED means
    still serving but shedding or saturated; UNREADY means stop sending
    traffic (drained or persistently failing).
"""
from __future__ import annotations

import math
import time
from typing import Callable, Optional

__all__ = ["Overloaded", "DeadlineExceeded", "StepGuard",
           "HealthStateMachine", "HealthState"]


class Overloaded(RuntimeError):
    """Request rejected at admission: the queue is at capacity."""


class DeadlineExceeded(RuntimeError):
    """Request abandoned: its deadline expired before completion."""


# -- training: non-finite step guard -----------------------------------------

class StepGuard:
    """Non-finite-loss step policy for a training loop.

    ``observe(loss_value)`` returns one of:
      * ``"ok"``       — finite loss, take the step;
      * ``"skip"``     — non-finite, skip the optimizer step;
      * ``"rollback"`` — the K-th consecutive non-finite step AND a
        restore hook is configured: the guard already invoked it; the
        caller should also skip this step (the restored weights take
        over from the next batch).

    The consecutive counter resets on any finite loss, so isolated
    spikes only cost their own step. Counters (``skipped``, ``total``,
    ``rollbacks``) are mirrored into the registry as
    ``train_nonfinite_steps_total`` / ``recoveries_total``.
    """

    def __init__(self, rollback_after: Optional[int] = None,
                 restore_fn: Optional[Callable[[], object]] = None):
        if rollback_after is not None and rollback_after < 1:
            raise ValueError("rollback_after must be >= 1")
        self.rollback_after = rollback_after
        self.restore_fn = restore_fn
        self.consecutive = 0
        self.skipped = 0
        self.steps = 0
        self.rollbacks = 0

    def _metrics(self):
        from ..observability.metrics import get_registry
        reg = get_registry()
        return (reg.counter("train_nonfinite_steps_total",
                            "train steps skipped on a non-finite loss"),
                reg.counter("recoveries_total",
                            "successful recovery actions, by kind",
                            labelnames=("kind",)))

    def observe(self, loss_value: float) -> str:
        self.steps += 1
        if math.isfinite(loss_value):
            self.consecutive = 0
            return "ok"
        self.skipped += 1
        self.consecutive += 1
        skipped_c, recoveries_c = self._metrics()
        skipped_c.inc()
        if (self.rollback_after is not None
                and self.consecutive >= self.rollback_after
                and self.restore_fn is not None):
            self.restore_fn()
            self.rollbacks += 1
            self.consecutive = 0
            recoveries_c.labels(kind="rollback").inc()
            return "rollback"
        return "skip"


# -- serving: health/readiness state machine ---------------------------------

class HealthState:
    STARTING = "starting"
    READY = "ready"
    DEGRADED = "degraded"
    UNREADY = "unready"


_STATE_CODE = {HealthState.STARTING: 0, HealthState.READY: 1,
               HealthState.DEGRADED: 2, HealthState.UNREADY: 3}


class HealthStateMachine:
    """Readiness surface for a serving engine.

    STARTING until the first successful step; READY while healthy;
    DEGRADED while the queue sits above ``degraded_queue_frac`` of
    capacity or a shed/deadline event happened within ``degraded_hold_s``
    (hysteresis — one shed must not flap the probe); UNREADY after
    ``unready_after`` CONSECUTIVE step failures, or on ``drain()``.
    A later successful step recovers UNREADY → READY (drained engines
    stay down until ``reset()``).
    """

    def __init__(self, capacity: int, degraded_queue_frac: float = 0.8,
                 degraded_hold_s: float = 5.0, unready_after: int = 3,
                 engine: str = "serving"):
        self.capacity = max(1, capacity)
        self.degraded_queue_frac = degraded_queue_frac
        self.degraded_hold_s = degraded_hold_s
        self.unready_after = unready_after
        self.state = HealthState.STARTING
        self._consecutive_failures = 0
        self._last_degrade_event = -float("inf")
        self._drained = False
        from ..observability.metrics import get_registry
        self._gauge = get_registry().gauge(
            "serving_health_state",
            "0=starting 1=ready 2=degraded 3=unready",
            labelnames=("engine",)).labels(engine=engine)
        self._gauge.set(_STATE_CODE[self.state])

    # -- event feeds --------------------------------------------------------
    def on_step_ok(self, queue_depth: int):
        self._consecutive_failures = 0
        if self._drained:
            return
        now = time.monotonic()
        over = queue_depth >= self.degraded_queue_frac * self.capacity
        if over:
            self._last_degrade_event = now
        # over-capacity RIGHT NOW is degraded regardless of hold_s; the
        # hold only stretches how long a past event keeps us degraded
        degraded = over or (
            (now - self._last_degrade_event) < self.degraded_hold_s)
        self._set(HealthState.DEGRADED if degraded else HealthState.READY)

    def on_step_error(self):
        self._consecutive_failures += 1
        if self._consecutive_failures >= self.unready_after:
            self._set(HealthState.UNREADY)
        elif self.state != HealthState.STARTING:
            self._set(HealthState.DEGRADED)
            self._last_degrade_event = time.monotonic()

    def on_shed(self):
        self._last_degrade_event = time.monotonic()
        if self.state in (HealthState.READY, HealthState.STARTING):
            self._set(HealthState.DEGRADED)

    def drain(self):
        """Administrative: stop advertising readiness permanently (until
        reset) — the restart/upgrade path."""
        self._drained = True
        self._set(HealthState.UNREADY)

    def reset(self):
        self._drained = False
        self._consecutive_failures = 0
        self._last_degrade_event = -float("inf")
        self._set(HealthState.STARTING)

    # -- probes -------------------------------------------------------------
    def ready(self) -> bool:
        return self.state in (HealthState.READY, HealthState.DEGRADED)

    def _set(self, state: str):
        self.state = state
        self._gauge.set(_STATE_CODE[state])
