"""Telemetry-driven auto-remediation: alerts become actions.

Rounds 10–15 built the sensing plane — multi-window SLO burn-rate
alerts (``observability.slo``), typed fleet findings
(``observability.fleet``), and online anomaly spikes
(``observability.anomaly``) — but every signal terminated in a
dashboard. This module closes the loop: an ``AutoRemediator``
subscribes to all three streams, normalizes them into one ``Signal``
shape, maps them through a declarative policy table
(``PolicyRule(signal, action, hysteresis, cooldown_s)``) to typed
``RemediationAction``s, and executes those against the gateway's own
control surfaces:

  * ``drain_replica``   — ``Gateway.drain_replica(name, requeue=True)``
    (token-exact requeue; the straggler's in-flight work resumes on
    survivors)
  * ``restart_replica`` — forced remove + a fresh engine from the
    deployment's ``replica_factory`` under the same name
  * ``reroute_sessions``— ``SessionAffinityPolicy.forget_replica`` (sticky
    sessions re-route on their next turn)
  * ``shed_tenant``     — throttle the top-queued tenant's token bucket
    (restored automatically when the triggering SLO resolves)
  * ``scale_up`` / ``scale_down`` — delegated to an attached
    ``gateway.autoscaler.Autoscaler`` (or a bare ``replica_factory``)

A production remediator's failure mode is CAUSING the outage it is
meant to fix, so every action is triple-gated:

  1. **hysteresis** — a rule acts only after its signal fired on K
     CONSECUTIVE ticks (one noisy spike never drains anything);
  2. **per-(action, target) cooldown** — the same action on the same
     target within ``cooldown_s`` is suppressed (no
     drain → restart → drain churn on one replica);
  3. **global flap guard** — at most ``max_actions`` executed per
     ``window_s`` across ALL targets; breaching the budget freezes the
     remediator for ``freeze_s``, and every further breach DOUBLES the
     freeze (escalate-don't-oscillate: a remediator that keeps hitting
     its budget is fighting a fire it cannot put out, and backs off for
     a human instead of thrashing).

``dry_run`` journals intent without touching the pool. The
``PADDLE_REMEDIATE`` env var gates the whole loop at construction:
``0``/``off`` disables execution entirely, ``dry`` forces dry-run,
unset/``1`` leaves the constructor arguments in charge.

Every decision — executed, dry-run, or suppressed and why — lands in
the per-rank telemetry spool (``remediation`` events, the
``telemetry_dump --actions`` timeline), the crash-surviving flight
recorder, and ``remediator.*`` registry series.
"""
from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..observability.fleet import FleetFinding, spool_event
from ..observability.flight import flight_record
from ..utils.locks import TracedLock

__all__ = ["Signal", "PolicyRule", "RemediationAction", "FlapGuard",
           "AutoRemediator", "DEFAULT_POLICY", "ACTION_KINDS",
           "remediate_enabled"]

ACTION_KINDS = ("drain_replica", "restart_replica", "reroute_sessions",
                "shed_tenant", "scale_up", "scale_down")

# decision outcomes a proposal can land on (journaled verbatim)
_EXECUTED = "executed"
_DRY_RUN = "dry_run"
_DISABLED = "disabled"


def remediate_enabled(default: bool = True) -> bool:
    """The ``PADDLE_REMEDIATE`` master gate (``0``/``off``/``false``
    disables; anything else leaves ``default`` in charge)."""
    v = os.environ.get("PADDLE_REMEDIATE", "").strip().lower()
    if v in ("0", "off", "false", "no"):
        return False
    return default


@dataclass(frozen=True)
class Signal:
    """One normalized input event, whatever plane it came from.

    kind: ``tpot_spike`` / ``ttft_spike`` / ``queue_depth_spike``
    (anomaly), ``straggler`` / ``desync`` / ``missing_rank`` (fleet),
    ``slo_breach:<slo>`` / ``slo_resolved:<slo>`` (burn-rate monitor).
    target: the implicated replica/tenant when the source names one.
    """

    kind: str
    target: Optional[str] = None
    severity: str = ""
    detail: tuple = ()          # frozen (k, v) pairs for hashability

    def detail_dict(self) -> dict:
        return dict(self.detail)


@dataclass(frozen=True)
class PolicyRule:
    """One row of the declarative policy table: when ``signal`` has
    fired on ``hysteresis`` consecutive ticks, take ``action`` (subject
    to the per-target cooldown and the global flap guard)."""

    signal: str
    action: str
    hysteresis: int = 2
    cooldown_s: float = 60.0

    def __post_init__(self):
        if self.action not in ACTION_KINDS:
            raise ValueError(f"unknown action {self.action!r} "
                             f"(one of {ACTION_KINDS})")
        if self.hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")


# the default table: anomaly spikes that NAME a replica drain/reroute
# it; queue pressure scales up; a sustained TTFT SLO burn sheds the
# top-queued tenant (and un-sheds on resolution); a fleet missing_rank
# restarts. Deployments override by passing their own table.
DEFAULT_POLICY: Tuple[PolicyRule, ...] = (
    PolicyRule("tpot_spike", "drain_replica", hysteresis=2,
               cooldown_s=60.0),
    PolicyRule("ttft_spike", "reroute_sessions", hysteresis=2,
               cooldown_s=60.0),
    PolicyRule("straggler", "drain_replica", hysteresis=2,
               cooldown_s=60.0),
    PolicyRule("missing_rank", "restart_replica", hysteresis=1,
               cooldown_s=120.0),
    PolicyRule("queue_depth_spike", "scale_up", hysteresis=3,
               cooldown_s=90.0),
    PolicyRule("slo_breach:gateway_ttft", "shed_tenant", hysteresis=2,
               cooldown_s=120.0),
)


@dataclass
class RemediationAction:
    """One decided action (executed or not — ``decision`` says which)."""

    kind: str
    target: str
    signal: str
    decision: str               # executed | dry_run | disabled | the
    #                             suppression reason (cooldown, flap_*,
    #                             no_target, last_replica, no_factory)
    reason: str
    at: float
    detail: dict = field(default_factory=dict)

    @property
    def executed(self) -> bool:
        return self.decision == _EXECUTED

    def to_dict(self) -> dict:
        return {"kind": self.kind, "target": self.target,
                "signal": self.signal, "decision": self.decision,
                "reason": self.reason, "at": self.at,
                "detail": dict(self.detail)}


class FlapGuard:
    """Global action budget with an escalate-don't-oscillate ladder.

    At most ``max_actions`` executed actions per rolling ``window_s``.
    A proposal over budget is rejected AND freezes the guard for
    ``freeze_s``; every subsequent breach doubles the freeze (capped at
    ``max_freeze_s``). A healthy stretch (no breach for a full window)
    resets the ladder.
    """

    def __init__(self, max_actions: int = 4, window_s: float = 60.0,
                 freeze_s: float = 120.0, max_freeze_s: float = 3600.0,
                 clock: Callable[[], float] = time.monotonic):
        if max_actions < 1:
            raise ValueError("max_actions must be >= 1")
        self.max_actions = int(max_actions)
        self.window_s = float(window_s)
        self.freeze_s = float(freeze_s)
        self.max_freeze_s = float(max_freeze_s)
        self._clock = clock
        self._times: deque = deque()
        self._freeze_until = 0.0
        self._last_breach = -float("inf")
        self.escalations = 0

    def _prune(self, now: float):
        while self._times and self._times[0] <= now - self.window_s:
            self._times.popleft()

    @property
    def frozen_until(self) -> float:
        return self._freeze_until

    def check(self, now: Optional[float] = None) -> Tuple[bool, str]:
        """(allowed, reason-if-not). Checking over budget escalates."""
        now = self._clock() if now is None else now
        if now < self._freeze_until:
            return False, "flap_frozen"
        self._prune(now)
        # the ladder re-arms only after a full CALM window — and frozen
        # time is not calm (nothing could act), so calm is measured from
        # whichever ended later: the last breach or the freeze it caused
        if now - max(self._last_breach, self._freeze_until) \
                > self.window_s:
            self.escalations = 0
        if len(self._times) >= self.max_actions:
            self.escalations += 1
            self._last_breach = now
            freeze = min(self.max_freeze_s,
                         self.freeze_s * (2 ** (self.escalations - 1)))
            self._freeze_until = now + freeze
            return False, "flap_budget"
        return True, ""

    def record(self, now: Optional[float] = None):
        self._times.append(self._clock() if now is None else now)


class AutoRemediator:
    """The closed remediation loop over one ``Gateway``.

    gw: the gateway whose pool/router/quotas the actions touch.
    monitor: an ``observability.slo.SLOMonitor`` (polled every tick;
    its alerts/resolutions become ``slo_breach:*`` / ``slo_resolved:*``
    signals). detector: an ``observability.anomaly.AnomalyDetector``
    (new findings consumed by index — pair it with a ``GatewayProbe``
    for the online feed). fleet_findings: a zero-arg callable returning
    ``FleetFinding``s (e.g. a bound ``FleetAggregator`` scan); consumed
    once each by (kind, op, seq) identity. policy: the rule table
    (default ``DEFAULT_POLICY``). replica_factory: ``name -> batcher``
    for restart/scale actions. autoscaler: an attached
    ``gateway.autoscaler.Autoscaler`` scale_up/scale_down delegate to.
    dry_run: journal intent, touch nothing. clock: injectable time.

    Drive ``tick()`` alongside ``gw.step()`` — it is synchronous,
    deterministic, and cheap when nothing fires.
    """

    def __init__(self, gw, monitor=None, detector=None,
                 fleet_findings: Optional[Callable[[], Sequence[FleetFinding]]] = None,
                 policy: Sequence[PolicyRule] = DEFAULT_POLICY,
                 replica_factory: Optional[Callable[[str], object]] = None,
                 autoscaler=None, dry_run: bool = False,
                 flap_guard: Optional[FlapGuard] = None,
                 min_routable: int = 1,
                 shed_factor: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        self.gw = gw
        self.monitor = monitor
        self.detector = detector
        self.fleet_findings = fleet_findings
        self.policy = list(policy)
        self.replica_factory = replica_factory
        self.autoscaler = autoscaler
        self.dry_run = (True if os.environ.get(
            "PADDLE_REMEDIATE", "").strip().lower() == "dry" else dry_run)
        self.enabled = remediate_enabled()
        self.flap_guard = flap_guard or FlapGuard(clock=clock)
        self.min_routable = int(min_routable)
        self.shed_factor = float(shed_factor)
        self._clock = clock
        self.actions: List[RemediationAction] = []   # every decision
        self._alert_idx = 0
        self._resolved_idx = 0
        self._finding_idx = 0
        self._fleet_seen: set = set()
        # tick-state lock: guards the hysteresis streaks and the action
        # journal against an off-thread reader (summary()/executed() from
        # a telemetry poller). Never held across _propose/_execute — those
        # call into the gateway, and the only cross-object lock order is
        # AutoRemediator._tick -> Gateway._admit.
        self._tick_lock = TracedLock("AutoRemediator._tick")
        # hysteresis counters: (rule.signal, rule.action, target) →
        # consecutive ticks the signal fired
        self._streak: Dict[Tuple[str, str, str], int] = {}
        # cooldowns: (action, target) → last EXECUTED time
        self._cooldown: Dict[Tuple[str, str], float] = {}
        # shed_tenant undo state: tenant → original bucket
        self._shed_orig: Dict[str, object] = {}
        self._restart_seq = 0
        from ..observability.metrics import get_registry
        reg = get_registry()
        self._signals_c = reg.counter(
            "remediator.signals_total", "normalized input signals seen",
            labelnames=("kind",))
        self._actions_c = reg.counter(
            "remediator.actions_total",
            "remediation decisions, by action and outcome",
            labelnames=("action", "decision"))
        self._frozen_g = reg.gauge(
            "remediator.frozen",
            "1 while the flap guard has the remediator frozen")

    # -- signal collection ----------------------------------------------------
    def _collect(self, now: float) -> List[Signal]:
        out: List[Signal] = []
        if self.monitor is not None:
            self.monitor.poll(now)
            for a in self.monitor.alerts[self._alert_idx:]:
                out.append(Signal(kind=f"slo_breach:{a.slo}",
                                  severity=a.severity,
                                  detail=(("burn_fast", a.burn_fast),
                                          ("burn_slow", a.burn_slow))))
            self._alert_idx = len(self.monitor.alerts)
            for r in getattr(self.monitor, "resolutions", ())[
                    self._resolved_idx:]:
                out.append(Signal(kind=f"slo_resolved:{r.slo}",
                                  severity=r.severity,
                                  detail=(("duration_s", r.duration_s),)))
            self._resolved_idx = len(self.monitor.resolutions)
        if self.detector is not None:
            for f in self.detector.findings[self._finding_idx:]:
                out.append(self._from_finding(f))
            self._finding_idx = len(self.detector.findings)
        if self.fleet_findings is not None:
            for f in self.fleet_findings():
                key = (f.kind, f.op, f.seq)
                if key in self._fleet_seen:
                    continue
                self._fleet_seen.add(key)
                out.append(self._from_finding(f))
        for s in out:
            self._signals_c.labels(kind=s.kind).inc()
        return out

    @staticmethod
    def _from_finding(f: FleetFinding) -> Signal:
        # anomaly findings carry the replica/series name in
        # detail["key"]; fleet findings implicate a rank
        target = f.detail.get("key")
        if target is None and f.rank is not None:
            target = f"rank{f.rank}"
        detail = tuple(sorted(
            (k, v) for k, v in f.detail.items()
            if isinstance(v, (int, float, str, bool, type(None)))))
        return Signal(kind=f.kind, target=target, detail=detail)

    # -- the decision tick ----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[RemediationAction]:
        """Collect signals, advance hysteresis, decide, execute.
        Returns the decisions made during THIS call (executed or not)."""
        now = self._clock() if now is None else now
        signals = self._collect(now)
        # worst-first: when one fault degrades several replicas at once
        # (the straggler's survivors absorb its load and slow down too),
        # the HIGHEST-scoring anomaly must win the action budget — not
        # whichever replica happened to step first this tick
        signals.sort(key=lambda s: -float(
            s.detail_dict().get("score") or 0.0))
        self._frozen_g.set(
            1 if now < self.flap_guard.frozen_until else 0)
        decided: List[RemediationAction] = []
        fired_keys: set = set()
        for sig in signals:
            for rule in self.policy:
                if rule.signal != sig.kind:
                    continue
                target = self._resolve_target(rule.action, sig)
                key = (rule.signal, rule.action, target or "")
                fired_keys.add(key)
                with self._tick_lock:
                    streak = self._streak.get(key, 0) + 1
                    self._streak[key] = streak
                if streak < rule.hysteresis:
                    continue
                act = self._propose(rule, sig, target, now)
                decided.append(act)
                if act.executed:
                    with self._tick_lock:
                        self._streak[key] = 0
            # resolution signals also un-shed outside the policy table:
            # the shed is lifted when the incident that caused it closes
            if sig.kind.startswith("slo_resolved:") and self._shed_orig:
                decided.extend(self._unshed_all(sig, now))
        # a tick where a signal did NOT fire resets its streak —
        # hysteresis means K CONSECUTIVE firings
        with self._tick_lock:
            for key in [k for k in self._streak if k not in fired_keys]:
                self._streak[key] = 0
            self.actions.extend(decided)
        return decided

    def _resolve_target(self, action: str, sig: Signal) -> Optional[str]:
        if action in ("drain_replica", "restart_replica",
                      "reroute_sessions"):
            t = sig.target
            return t if (t is not None and t in self.gw.pool) else None
        if action == "shed_tenant":
            return self._top_tenant()
        return "pool"       # scale_up / scale_down

    def _top_tenant(self) -> Optional[str]:
        """The tenant with the most queued requests — the shed target
        when an SLO burns without a named culprit. Falls back to ALL
        live requests when nothing is queued at this instant (a burn
        alert can land on a tick where the backlog just dispatched)."""
        counts: Dict[str, int] = {}
        for req in self.gw._requests.values():
            if req.replica is None:
                counts[req.tenant] = counts.get(req.tenant, 0) + 1
        if not counts:
            for req in self.gw._requests.values():
                counts[req.tenant] = counts.get(req.tenant, 0) + 1
        if not counts:
            return None
        return max(counts.items(), key=lambda kv: (kv[1], kv[0]))[0]

    # -- proposal gating + execution ------------------------------------------
    def _propose(self, rule: PolicyRule, sig: Signal,
                 target: Optional[str], now: float) -> RemediationAction:
        def make(decision: str, reason: str,
                 **detail) -> RemediationAction:
            act = RemediationAction(
                kind=rule.action, target=target or "", signal=sig.kind,
                decision=decision, reason=reason, at=now, detail=detail)
            self._journal(act)
            return act

        if target is None:
            return make("no_target",
                        f"{sig.kind} names no live pool member")
        if not self.enabled:
            return make(_DISABLED, "PADDLE_REMEDIATE=0")
        last = self._cooldown.get((rule.action, target))
        if last is not None and now - last < rule.cooldown_s:
            return make("cooldown",
                        f"{rule.action} on {target} "
                        f"{now - last:.1f}s ago (< {rule.cooldown_s}s)")
        if rule.action in ("drain_replica", "restart_replica") \
                and self._would_strand(target):
            return make("last_replica",
                        f"{target} is the last routable replica")
        if self.dry_run:
            self._cooldown[(rule.action, target)] = now
            return make(_DRY_RUN, f"would {rule.action} {target}")
        ok, why = self.flap_guard.check(now)
        if not ok:
            self._frozen_g.set(1)
            return make(why, f"flap guard rejected {rule.action} "
                             f"(escalation {self.flap_guard.escalations})")
        try:
            detail = self._execute(rule.action, target, sig) or {}
        except Exception as exc:  # noqa: BLE001 — a failed remediation
            # must never take the control loop down with it
            return make("error", f"{type(exc).__name__}: {exc}")
        self.flap_guard.record(now)
        self._cooldown[(rule.action, target)] = now
        return make(_EXECUTED, f"{sig.kind} -> {rule.action} {target}",
                    **detail)

    def _would_strand(self, target: str) -> bool:
        routable = [r.name for r in self.gw.pool.routable()]
        return (target in routable
                and len(routable) <= self.min_routable)

    def _execute(self, action: str, target: str,
                 sig: Signal) -> Optional[dict]:
        gw = self.gw
        if action == "drain_replica":
            rep = gw.pool.get(target)
            inflight = rep.load
            # durable sessions ride the drain untouched: the gateway
            # preserves the replica's session pins (its tiered chains
            # stay resumable) and manifests live in the shared store
            pins = len(getattr(rep.batcher, "_session_pins", {}) or {})
            gw.drain_replica(target, requeue=True)
            return {"requeued": inflight, "sessions_preserved": pins}
        if action == "restart_replica":
            if self.replica_factory is None:
                raise RuntimeError("no replica_factory configured")
            gw.remove_replica(target, force=True)
            self._restart_seq += 1
            gw.add_replica(target, self.replica_factory(target))
            return {"generation": self._restart_seq}
        if action == "reroute_sessions":
            router = gw.router
            if hasattr(router, "forget_replica"):
                router.forget_replica(target)
            return None
        if action == "shed_tenant":
            quotas = gw.quotas
            orig = quotas.bucket(target)
            if target not in self._shed_orig:
                self._shed_orig[target] = orig
            from ..inference.gateway.quota import TokenBucket
            if orig is not None:
                throttled = TokenBucket(orig.rate * self.shed_factor,
                                        max(1.0, orig.burst
                                            * self.shed_factor))
            else:
                # un-quota'd tenant: impose a tight emergency bucket
                throttled = TokenBucket(rate=64.0, burst=256.0)
            quotas.set_quota(target, throttled)
            return {"factor": self.shed_factor}
        if action in ("scale_up", "scale_down"):
            if self.autoscaler is not None:
                n = (self.autoscaler.scale_up(reason=sig.kind)
                     if action == "scale_up"
                     else self.autoscaler.scale_down(reason=sig.kind))
                return {"replica": n}
            if action == "scale_up":
                if self.replica_factory is None:
                    raise RuntimeError(
                        "no autoscaler or replica_factory configured")
                self._restart_seq += 1
                name = f"auto{self._restart_seq}"
                gw.add_replica(name, self.replica_factory(name))
                return {"replica": name}
            # scale_down without an autoscaler: drain the least-loaded
            cands = sorted(gw.pool.routable(), key=lambda r: r.load)
            if len(cands) <= self.min_routable:
                raise RuntimeError("pool already at min_routable")
            gw.drain_replica(cands[0].name, requeue=True)
            return {"replica": cands[0].name}
        raise ValueError(f"unknown action {action!r}")

    def _unshed_all(self, sig: Signal,
                    now: float) -> List[RemediationAction]:
        out = []
        for tenant, orig in list(self._shed_orig.items()):
            if not self.dry_run and self.enabled:
                if orig is None:
                    self.gw.quotas._buckets.pop(tenant, None)
                else:
                    self.gw.quotas.set_quota(tenant, orig)
            act = RemediationAction(
                kind="shed_tenant", target=tenant, signal=sig.kind,
                decision=_EXECUTED if (self.enabled and not self.dry_run)
                else _DRY_RUN,
                reason=f"restored quota on {sig.kind}", at=now,
                detail={"restore": 1})
            self._journal(act)
            out.append(act)
            del self._shed_orig[tenant]
        return out

    # -- journaling -----------------------------------------------------------
    def _journal(self, act: RemediationAction):
        self._actions_c.labels(action=act.kind,
                               decision=act.decision).inc()
        spool_event("remediation", action=act.kind, target=act.target,
                    signal=act.signal, decision=act.decision,
                    reason=act.reason, **{
                        k: v for k, v in act.detail.items()
                        if isinstance(v, (int, float, str, bool))})
        flight_record("remediation", action=act.kind, target=act.target,
                      decision=act.decision)

    # -- introspection --------------------------------------------------------
    def executed(self) -> List[RemediationAction]:
        with self._tick_lock:
            return [a for a in self.actions if a.executed]

    def summary(self) -> dict:
        by: Dict[str, Dict[str, int]] = {}
        with self._tick_lock:
            actions = list(self.actions)
        for a in actions:
            by.setdefault(a.kind, {}).setdefault(a.decision, 0)
            by[a.kind][a.decision] += 1
        return {"decisions": len(actions),
                "executed": sum(1 for a in actions if a.executed),
                "by_action": by,
                "flap_escalations": self.flap_guard.escalations,
                "dry_run": self.dry_run, "enabled": self.enabled}
