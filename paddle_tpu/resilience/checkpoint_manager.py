"""Crash-safe checkpoint lifecycle over the sharded save/load.

``CheckpointManager`` owns a checkpoint ROOT holding one directory per
step and composes the guarantees the raw ``save_state_dict`` /
``load_state_dict`` pair (distributed/checkpoint/save_load.py) does not:

  * ATOMIC PUBLISH — a save writes into a hidden temp directory, drops a
    terminal ``COMMITTED`` marker as its last file, then ``os.replace``s
    the whole directory to its final ``step_N`` name. A kill at ANY byte
    offset of any file leaves either (a) a garbage temp dir the next
    save sweeps away, or (b) a fully-published checkpoint — never a
    half-written "latest".
  * INTEGRITY — per-array crc32 checksums ride the chunk metadata
    (LocalTensorMetadata.checksum); ``validate()`` re-hashes every chunk.
  * FALLBACK RESTORE — ``restore_latest()`` walks steps newest-first and
    restores the newest checkpoint that VALIDATES, silently skipping
    corrupt/uncommitted ones (counted, and surfaced in telemetry as
    ``checkpoint_invalid_total`` + ``recoveries_total{kind=
    checkpoint_fallback}``).
  * RETENTION — keep-last-N published steps; temp debris is swept.
  * ASYNC — ``save(..., blocking=False)`` publishes on a background
    thread (``wait()`` joins and re-raises the first failure).
  * RETRY — transient I/O failures (including injected
    ``transient_error`` chaos at ``checkpoint.write``) retry under the
    shared ``RetryPolicy``; torn writes are crashes and propagate.
"""
from __future__ import annotations

import glob
import json
import os
import pickle
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .retry import RetryPolicy

__all__ = ["CheckpointManager", "COMMITTED_MARKER", "validate_checkpoint",
           "CheckpointFinding", "write_committed_marker"]

COMMITTED_MARKER = "COMMITTED"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp-"


@dataclass
class CheckpointFinding:
    """One typed restore-time diagnosis — the checkpoint analog of
    ``observability.fleet.FleetFinding``. ``restore_latest`` emits one
    per checkpoint it DISCARDS on the way to the newest valid step, so
    a fallback is never silent: the finding names what was wrong
    (``uncommitted`` / ``checksum_mismatch`` / ``missing_ack`` /
    ``missing_shard`` / ``unreadable`` / ``torn_step``) and which step
    was skipped."""
    kind: str
    step: int
    reason: str
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "step": self.step,
                "reason": self.reason, "detail": dict(self.detail)}

    def __str__(self):
        return f"{self.kind}: step={self.step} {self.reason}"


def classify_invalid_reason(reason: str) -> str:
    """Map a ``validate_checkpoint`` reason string onto a finding kind."""
    if "torn step" in reason:
        return "torn_step"
    if "COMMITTED" in reason:
        return "uncommitted"
    if "checksum" in reason:
        return "checksum_mismatch"
    if "unreadable" in reason:
        return "unreadable"
    if "ack" in reason:
        return "missing_ack"
    if "shard file" in reason or "MANIFEST" in reason:
        return "missing_shard"
    return "invalid"


def write_committed_marker(dirpath: str, step: int,
                           extra: Optional[dict] = None,
                           chaos_point: Optional[str] = None) -> str:
    """The ONE terminal-marker writer both checkpoint managers share:
    the marker is fsync'd and (when ``chaos_point`` names a seam)
    written through the chaos torn-write plumbing so publish drills can
    tear it. Any directory carrying the marker holds a complete file
    set — writing it is the commit point."""
    marker = os.path.join(dirpath, COMMITTED_MARKER)
    payload = dict(extra or {})
    payload["step"] = step
    data = json.dumps(payload).encode()
    if chaos_point is not None:
        from .chaos import torn_write_bytes
        tmp = marker + ".tmp"
        torn_write_bytes(tmp, data, point=chaos_point)
        os.replace(tmp, marker)
    else:
        with open(marker, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
    return marker


def validate_checkpoint(path: str) -> Tuple[bool, str]:
    """(ok, reason). ok=True means the directory is committed, every
    metadata file unpickles, and every chunk matches its stored checksum
    (chunks saved before checksums existed — no ``checksum`` attribute —
    pass, preserving old-checkpoint compatibility)."""
    import numpy as np

    from ..distributed.checkpoint.metadata import Metadata, chunk_crc
    if not os.path.isdir(path):
        return False, "not a directory"
    if not os.path.exists(os.path.join(path, COMMITTED_MARKER)):
        return False, "no COMMITTED marker"
    meta_files = sorted(glob.glob(os.path.join(path, "metadata.*.pkl")))
    legacy = os.path.join(path, "metadata.pkl")
    if os.path.exists(legacy):
        meta_files.append(legacy)
    if not meta_files:
        return False, "no metadata files"
    try:
        npz_cache: Dict[str, object] = {}
        for fn in meta_files:
            with open(fn, "rb") as f:
                meta: Metadata = pickle.load(f)
            for key, tmeta in meta.state_dict_metadata.items():
                for chunk in tmeta.chunks:
                    want = getattr(chunk, "checksum", None)
                    if want is None:
                        continue  # pre-checksum checkpoint
                    cid = Metadata.chunk_id(key, chunk.global_offset)
                    fname = meta.storage_metadata[cid]
                    if fname not in npz_cache:
                        npz_cache[fname] = np.load(
                            os.path.join(path, fname))
                    got = chunk_crc(npz_cache[fname][cid])
                    if got != want:
                        return False, (f"checksum mismatch for {cid} "
                                       f"({got:#x} != {want:#x})")
    except Exception as exc:  # noqa: BLE001 — any unreadable byte = invalid
        return False, f"unreadable ({type(exc).__name__}: {exc})"
    finally:
        for f in npz_cache.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass
    return True, "ok"


class CheckpointManager:
    """Atomic-publish checkpoint store rooted at one directory."""

    def __init__(self, root: str, keep_last: int = 3,
                 retry: Optional[RetryPolicy] = None):
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        self.root = root
        self.keep_last = keep_last
        self.retry = retry or RetryPolicy(max_attempts=3, base_delay=0.02,
                                          max_delay=0.5)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._errors: List[BaseException] = []
        self._tmp_seq = 0
        self.invalid_skipped = 0      # corrupt checkpoints seen by restore
        #: typed CheckpointFinding records for every checkpoint a restore
        #: DISCARDED (newest first); cleared at each restore_latest call
        self.findings: List[CheckpointFinding] = []

    # -- directory layout ---------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"{_STEP_PREFIX}{step:012d}")

    def steps(self) -> List[int]:
        """Published steps, ascending (committed or not — see validate)."""
        out = []
        for name in os.listdir(self.root):
            if name.startswith(_STEP_PREFIX):
                try:
                    out.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    # -- save ----------------------------------------------------------------
    def save(self, state_dict: Dict, step: int,
             blocking: bool = True) -> str:
        """Publish `state_dict` as step `step`. blocking=False snapshots
        device arrays to host NOW (inside save_state_dict) but runs the
        file I/O + publish on a background thread; join with wait()."""
        final = self._step_dir(step)
        if blocking:
            self._publish(state_dict, step, final)
            return final

        def run():
            try:
                self._publish(state_dict, step, final)
            except BaseException as exc:  # noqa: BLE001 — surfaced by wait()
                self._errors.append(exc)

        t = threading.Thread(target=run, daemon=True,
                             name=f"ckpt-save-{step}")
        t.start()
        self._threads.append(t)
        return final

    def wait(self):
        """Join outstanding async saves; re-raise the first failure."""
        while self._threads:
            self._threads.pop().join()
        if self._errors:
            raise self._errors.pop(0)

    def _publish(self, state_dict: Dict, step: int, final: str):
        from ..distributed.checkpoint.save_load import save_state_dict
        with self._lock:
            self._tmp_seq += 1
            tmp = os.path.join(
                self.root,
                f"{_TMP_PREFIX}{_STEP_PREFIX}{step}-{os.getpid()}"
                f"-{self._tmp_seq}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        try:
            # transient I/O errors retry (each inner file write is itself
            # temp+replace, so a retried save just overwrites); a torn
            # write is a CRASH and propagates out of the retry filter
            self.retry.call(save_state_dict, state_dict, tmp,
                            point="checkpoint.write")
            # terminal marker: written LAST inside the temp dir, so any
            # directory carrying it holds a complete file set
            write_committed_marker(tmp, step)
            with self._lock:
                if os.path.exists(final):
                    shutil.rmtree(final)   # idempotent re-save of a step
                os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        from ..observability.flight import flight_record
        flight_record("checkpoint_commit", step=step)
        self._apply_retention()

    def _apply_retention(self):
        with self._lock:
            steps = self.steps()
            # only COMMITTED steps count toward keep_last: a run that
            # tears several saves in a row must not age out its last
            # good checkpoint. Torn/uncommitted dirs older than the
            # retention horizon are swept with it; newer ones stay (the
            # next restore's findings name them).
            committed = [s for s in steps if os.path.exists(
                os.path.join(self._step_dir(s), COMMITTED_MARKER))]
            if len(committed) >= self.keep_last:
                horizon = committed[-self.keep_last]
                for s in steps:
                    if s < horizon:
                        shutil.rmtree(self._step_dir(s),
                                      ignore_errors=True)
            # sweep temp debris from crashed saves of THIS root
            for d in glob.glob(os.path.join(self.root, _TMP_PREFIX + "*")):
                try:
                    age = time.time() - os.path.getmtime(d)
                except OSError:
                    continue
                if age > 60.0:   # live async saves are younger than this
                    shutil.rmtree(d, ignore_errors=True)

    # -- restore -------------------------------------------------------------
    def validate(self, step: int) -> Tuple[bool, str]:
        return validate_checkpoint(self._step_dir(step))

    def restore_latest(self, state_dict: Dict, **kwargs) -> Optional[int]:
        """Fill `state_dict` in place from the newest VALID checkpoint;
        returns its step, or None when no valid checkpoint exists.
        Corrupt/uncommitted newer checkpoints are skipped — each skip is
        a typed ``CheckpointFinding`` on ``self.findings`` (plus a
        flight-recorder ``ckpt.skip`` event and the
        ``checkpoint_invalid_total`` counter), never a silent fallback."""
        restore_h, invalid_c, recoveries_c = self._metrics()
        self.findings = []
        for step in reversed(self.steps()):
            ok, reason = self.validate(step)
            if not ok:
                self._record_skip(step, reason, invalid_c)
                continue
            t0 = time.perf_counter()
            self._do_restore(state_dict, step, **kwargs)
            dt = time.perf_counter() - t0
            restore_h.observe(dt)
            self._dotted_restore_seconds().observe(dt)
            from ..observability.flight import flight_record
            flight_record("ckpt.restore", step=step,
                          skipped=len(self.findings))
            if self.findings:
                recoveries_c.labels(kind="checkpoint_fallback").inc()
            return step
        return None

    def _do_restore(self, state_dict: Dict, step: int, **kwargs) -> None:
        """Layout-specific load of one validated step (subclass seam)."""
        from ..distributed.checkpoint.save_load import load_state_dict
        load_state_dict(state_dict, self._step_dir(step))

    def _classify_skip(self, step: int, reason: str) -> CheckpointFinding:
        return CheckpointFinding(kind=classify_invalid_reason(reason),
                                 step=step, reason=reason)

    def _record_skip(self, step: int, reason: str, invalid_c) -> None:
        finding = self._classify_skip(step, reason)
        self.findings.append(finding)
        self.invalid_skipped += 1
        invalid_c.inc()
        from ..observability.flight import flight_record
        flight_record("ckpt.skip", step=step, ckpt_kind=finding.kind)

    def _dotted_restore_seconds(self):
        from ..observability.metrics import get_registry
        return get_registry().histogram(
            "checkpoint.restore_seconds",
            "restore_latest wall time (validated step load)")

    def _metrics(self):
        from ..observability.metrics import get_registry
        reg = get_registry()
        return (reg.histogram("checkpoint_restore_seconds",
                              "restore_latest load time"),
                reg.counter("checkpoint_invalid_total",
                            "corrupt/uncommitted checkpoints skipped"),
                reg.counter("recoveries_total",
                            "successful recovery actions, by kind",
                            labelnames=("kind",)))
