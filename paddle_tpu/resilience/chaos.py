"""Deterministic, seed-driven fault injection.

Reference surface: the chaos/fault-drill tooling every production serving
stack grows (MegaScale-style fault attribution needs reproducible faults
to attribute) — here a registry of NAMED fault points woven into the
hot seams of this codebase:

  * ``checkpoint.write``  — sharded checkpoint file writes (save_load.py)
  * ``checkpoint.shard_write`` — one rank's shard-chunk/ack writes in the
    two-phase elastic save (resilience/sharded_checkpoint.py)
  * ``checkpoint.publish`` — rank 0's manifest + COMMITTED publish after
    it observed every shard ack (the phase-2 seam)
  * ``collective.enter``  — eager collective entry (collective.py)
  * ``serving.step``      — continuous-batcher step (inference/serving.py)
  * ``gateway.step.<replica>`` — ONE named replica's engine step in the
    gateway pool (gateway/replica.py; the shared ``serving.step`` point
    hits whichever replica steps next — this one targets a single
    replica, e.g. a ``delay`` makes exactly ``r1`` a straggler; error
    kinds bypass the retry policy and kill the replica outright)
  * ``kv.request``        — launcher master-KV requests (controllers.py)
  * ``kv.host_demote``    — spilling an evicted prefix block's KV rows to
    the host tier (inference/prefix_cache.py; a failure drops the chain
    instead of demoting — pages stay clean)
  * ``kv.host_promote``   — submitting a host->device prefix promotion
    (inference/serving.py; a failure degrades the admission to full
    prefill, token-exact)
  * ``kv.session_publish`` — the session-manifest atomic publish
    (inference/session_store.py; ``torn_write`` crashes the writer
    mid-manifest — only a ``.tmp`` no reader trusts is left behind, the
    previous manifest, if any, stays sound)
  * ``kv.session_resume`` — the manifest load at session resume
    (inference/session_store.py; a failure degrades the resume to full
    re-prefill from the caller's context, token-exact)
  * ``dataloader.next``   — batch delivery (io/dataloader.py)
  * ``train.step``        — hapi train_batch (hapi/model.py)

Fault kinds: ``delay`` (sleep), ``transient_error`` (raise a retryable
``TransientChaosError``), ``torn_write`` (the instrumented writer stops
mid-file at a chosen byte offset and raises ``TornWrite`` — a crash
mid-save), ``nan_grad`` (the train step's loss — and thus its gradients —
go NaN), ``kill_rank`` (``os._exit`` of a chosen rank in multi-process
worlds).

Determinism: firing decisions come from one ``random.Random(seed)`` plus
per-point hit counters — the SAME scenario spec against the same call
sequence fires at the same hit indices, so every chaos test replays.

Zero overhead when disabled: instrumented sites call ``fault_point(name)``
which is a single module-global check (``_ARMED``) before returning. A
site pays the registry lookup only while a scenario is armed.

Scenario specs (flag/env): ``PADDLE_CHAOS`` or ``arm_scenario(spec)``::

    seed=7; kv.request:transient_error:p=0.5,count=3; \
    checkpoint.write:torn_write:offset=128,after=1

i.e. ``;``-separated entries, each ``point:kind[:k=v,...]``, with an
optional ``seed=N`` entry applying to the whole scenario.
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "ChaosError", "TransientChaosError", "TornWrite", "FaultSpec",
    "ChaosRegistry", "get_chaos", "fault_point", "arm_scenario",
    "arm_from_env", "disarm", "parse_scenario", "FAULT_KINDS",
    "KNOWN_POINTS",
]

FAULT_KINDS = ("delay", "transient_error", "torn_write", "nan_grad",
               "kill_rank")

# the seams instrumented today (open set — arming an unknown point is
# allowed so new seams can be drilled before this list catches up;
# ``gateway.step.<replica>`` is a per-replica family, one point per
# pool member)
KNOWN_POINTS = ("checkpoint.write", "checkpoint.shard_write",
                "checkpoint.publish", "collective.enter", "serving.step",
                "gateway.step.<replica>",
                "kv.request", "kv.host_demote", "kv.host_promote",
                "kv.session_publish", "kv.session_resume",
                "dataloader.next", "train.step")


class ChaosError(RuntimeError):
    """Base class of every injected failure."""


class TransientChaosError(ChaosError):
    """A retryable injected failure (retry.py policies treat it as such)."""


class TornWrite(ChaosError):
    """Injected crash mid-write: the file was truncated at ``offset``."""

    def __init__(self, msg: str, offset: int):
        super().__init__(msg)
        self.offset = offset


@dataclass
class FaultSpec:
    """One armed fault: where, what, and when it fires.

    after: skip the first N hits of the point.
    count: fire at most N times (None = every eligible hit).
    p:     per-eligible-hit firing probability (seeded RNG → replayable).
    delay_s / offset / rank parameterize their kinds.
    """
    point: str
    kind: str
    after: int = 0
    count: Optional[int] = None
    p: float = 1.0
    delay_s: float = 0.05
    offset: int = 0              # torn_write: bytes written before the cut
    rank: Optional[int] = None   # kill_rank target (default: every rank)
    exit_code: int = 1
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")


# module-global fast path: fault_point() reads this before anything else
_ARMED = False
_LOCK = threading.Lock()


def _registry_metrics():
    from ..observability.metrics import get_registry
    reg = get_registry()
    return reg.counter("faults_injected_total",
                       "chaos faults fired, by point and kind",
                       labelnames=("point", "kind"))


class ChaosRegistry:
    """Armed fault specs + deterministic firing state."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._hits: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- arming -------------------------------------------------------------
    def arm(self, spec: FaultSpec) -> FaultSpec:
        global _ARMED
        with self._lock:
            self._specs.setdefault(spec.point, []).append(spec)
        _ARMED = True
        return spec

    def clear(self):
        global _ARMED
        with self._lock:
            self._specs.clear()
            self._hits.clear()
            self._rng = random.Random(self.seed)
        _ARMED = False

    def reseed(self, seed: int):
        with self._lock:
            self.seed = seed
            self._rng = random.Random(seed)

    @property
    def armed(self) -> bool:
        return bool(self._specs)

    def specs(self, point: Optional[str] = None) -> List[FaultSpec]:
        with self._lock:
            if point is not None:
                return list(self._specs.get(point, ()))
            return [s for ss in self._specs.values() for s in ss]

    def hits(self, point: str) -> int:
        """How many times the point has been reached (fired or not)."""
        return self._hits.get(point, 0)

    # -- firing -------------------------------------------------------------
    def _select(self, point: str) -> Optional[FaultSpec]:
        """Deterministically decide whether (and which) fault fires at
        this hit of `point`. Counters and the RNG advance under the lock
        so concurrent sites (serving + a background save) stay replayable
        per-point."""
        with self._lock:
            hit = self._hits.get(point, 0)
            self._hits[point] = hit + 1
            for spec in self._specs.get(point, ()):
                if hit < spec.after:
                    continue
                if spec.count is not None and spec.fired >= spec.count:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                spec.fired += 1
                return spec
        return None

    def fire(self, point: str) -> Optional[FaultSpec]:
        """Evaluate the point. Raises for error kinds, sleeps for delay,
        exits the process for a matching kill_rank, and RETURNS the spec
        for value kinds (torn_write, nan_grad) the site interprets."""
        spec = self._select(point)
        if spec is None:
            return None
        _registry_metrics().labels(point=point, kind=spec.kind).inc()
        # journal the injection BEFORE executing it: for kill_rank this
        # is the victim's last flight-ring entry — the post-mortem smoking
        # gun ("fault" key, not "kind": that slot names the event type)
        from ..observability.fleet import spool_event
        from ..observability.flight import flight_record
        flight_record("chaos", point=point, fault=spec.kind)
        spool_event("chaos", point=point, fault=spec.kind)
        if spec.kind == "delay":
            time.sleep(spec.delay_s)
            return None
        if spec.kind == "transient_error":
            raise TransientChaosError(
                f"injected transient failure at {point} "
                f"(hit {self._hits[point] - 1})")
        if spec.kind == "kill_rank":
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
            if spec.rank is None or spec.rank == rank:
                os._exit(spec.exit_code)
            return None
        # torn_write / nan_grad: the instrumented site owns the semantics
        return spec


_CHAOS = ChaosRegistry()


def get_chaos() -> ChaosRegistry:
    """The process-wide chaos registry."""
    return _CHAOS


def fault_point(name: str) -> Optional[FaultSpec]:
    """The hook instrumented sites call. One global check when disarmed."""
    if not _ARMED:
        return None
    return _CHAOS.fire(name)


# -- scenario specs ----------------------------------------------------------

_INT_KEYS = {"after", "count", "offset", "rank", "exit_code"}
_FLOAT_KEYS = {"p", "delay_s"}


def parse_scenario(spec: str) -> tuple[int, List[FaultSpec]]:
    """``seed=7; point:kind:k=v,...`` → (seed, [FaultSpec, ...])."""
    seed = 0
    out: List[FaultSpec] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            seed = int(entry[5:])
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(f"bad chaos entry {entry!r} "
                             f"(want point:kind[:k=v,...])")
        point, kind = parts[0].strip(), parts[1].strip()
        kw: Dict[str, object] = {}
        if len(parts) > 2 and parts[2].strip():
            for item in parts[2].split(","):
                k, _, v = item.partition("=")
                k = k.strip()
                if k in _INT_KEYS:
                    kw[k] = int(v)
                elif k in _FLOAT_KEYS:
                    kw[k] = float(v)
                else:
                    raise ValueError(f"unknown chaos option {k!r}")
        out.append(FaultSpec(point=point, kind=kind, **kw))
    return seed, out


def arm_scenario(spec: str) -> ChaosRegistry:
    """Parse and arm a scenario string on the process registry."""
    seed, specs = parse_scenario(spec)
    _CHAOS.clear()
    _CHAOS.reseed(seed)
    for s in specs:
        _CHAOS.arm(s)
    return _CHAOS


def arm_from_env(var: str = "PADDLE_CHAOS") -> Optional[ChaosRegistry]:
    """Arm from the environment (the launcher/CLI path); None if unset."""
    spec = os.environ.get(var)
    if not spec:
        return None
    return arm_scenario(spec)


def disarm():
    _CHAOS.clear()


# -- torn-write plumbing -----------------------------------------------------

def torn_write_bytes(path: str, data: bytes, point: str = "checkpoint.write"):
    """Write `data` to `path` honoring an armed ``torn_write`` fault: the
    fault cuts the file at ``spec.offset`` bytes and raises ``TornWrite``
    — exactly what a mid-write kill leaves on disk. Other kinds at the
    point (delay/transient_error) apply BEFORE any byte lands."""
    spec = fault_point(point)
    if spec is not None and spec.kind == "torn_write":
        cut = max(0, min(spec.offset, len(data)))
        with open(path, "wb") as f:
            f.write(data[:cut])
            f.flush()
            os.fsync(f.fileno())
        raise TornWrite(
            f"injected torn write at {point}: {cut}/{len(data)} bytes of "
            f"{path!r} written before the crash", cut)
    with open(path, "wb") as f:
        f.write(data)
