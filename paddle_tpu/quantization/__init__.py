"""Quantization framework (QAT + PTQ).

Reference: python/paddle/quantization — ``QuantConfig`` (config.py:
add_layer_config/add_type_config), ``QAT`` (qat.py: quantize -> swap layers
for quantized counterparts with fake quanters), ``PTQ`` (ptq.py: insert
observers, then convert), observers/quanters under observers/ + quanters/.

TPU-native: fake quantization is a quantize-dequantize pair emitted inline
(XLA fuses it into the surrounding matmul), and the straight-through
estimator is expressed as ``x + stop_gradient(q(x) - x)`` so the eager tape
differentiates it with no custom-grad machinery. int8 simulation keeps
tensors in float on the MXU — the TPU serving path consumes the scales.
"""
from __future__ import annotations

import copy
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer import Layer
from ..ops.registry import dispatch


# ---------------------------------------------------------------------------
# fake-quant primitives
# ---------------------------------------------------------------------------

def _fake_quant_ste(x, scale, bit_length=8):
    """Quantize-dequantize with straight-through gradient (pure jnp)."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    return x + jax.lax.stop_gradient(q - x)


def quant_dequant(x, scale, bit_length=8):
    """Public fake-quant op (tape-recorded; STE gradient)."""
    return dispatch(_fake_quant_ste, (x, scale), {"bit_length": bit_length},
                    op_name="fake_quant_dequant")


# ---------------------------------------------------------------------------
# observers / quanters (factory objects in the config, instances per layer)
# ---------------------------------------------------------------------------

class BaseObserver:
    """observers/base_observer.py analog: tracks a scale from data."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits

    def _instance(self):
        return copy.deepcopy(self)

    def observe(self, x: Tensor) -> None:
        raise NotImplementedError

    def scales(self) -> np.ndarray:
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """observers/abs_max.py analog: running max of |x|."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 0.0

    def observe(self, x):
        arr = np.asarray(x._data if isinstance(x, Tensor) else x)
        self._max = max(self._max, float(np.max(np.abs(arr), initial=0.0)))

    def scales(self):
        return np.float32(self._max if self._max > 0 else 1.0)


class EMAObserver(BaseObserver):
    """Exponential-moving-average absmax (observers/ema.py analog)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._val = None

    def observe(self, x):
        arr = np.asarray(x._data if isinstance(x, Tensor) else x)
        cur = float(np.max(np.abs(arr), initial=0.0))
        if self._val is None:
            self._val = cur
        else:
            self._val = (self.moving_rate * self._val
                         + (1 - self.moving_rate) * cur)

    def scales(self):
        return np.float32(self._val if self._val else 1.0)


class HistObserver(BaseObserver):
    """Percentile-of-histogram observer (observers/hist.py analog)."""

    def __init__(self, quant_bits=8, bins_count=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self.percent = percent
        self._hist = None
        self._edges = None

    def observe(self, x):
        arr = np.abs(np.asarray(x._data if isinstance(x, Tensor) else x))
        hi = float(arr.max(initial=0.0))
        if self._hist is None:
            self._edges = np.linspace(0, max(hi, 1e-8), self.bins_count + 1)
            self._hist = np.histogram(arr, bins=self._edges)[0].astype(
                np.float64)
        else:
            if hi > self._edges[-1]:  # re-bin into a wider range
                new_edges = np.linspace(0, hi, self.bins_count + 1)
                centers = (self._edges[:-1] + self._edges[1:]) / 2
                new_hist = np.histogram(centers, bins=new_edges,
                                        weights=self._hist)[0]
                self._edges, self._hist = new_edges, new_hist
            self._hist += np.histogram(arr, bins=self._edges)[0]

    def scales(self):
        if self._hist is None or self._hist.sum() == 0:
            return np.float32(1.0)
        cdf = np.cumsum(self._hist) / self._hist.sum()
        idx = int(np.searchsorted(cdf, self.percent))
        return np.float32(self._edges[min(idx + 1, len(self._edges) - 1)])


class FakeQuanterWithAbsMaxObserver(BaseObserver):
    """quanters/abs_max.py analog — QAT quanter: observes a moving absmax
    while fake-quantizing every forward."""

    def __init__(self, quant_bits=8, moving_rate=0.9, name=None, dtype=None):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._obs = EMAObserver(quant_bits, moving_rate)

    def observe(self, x):
        self._obs.observe(x)

    def scales(self):
        return self._obs.scales()

    def quantize(self, x: Tensor) -> Tensor:
        self.observe(x)
        return quant_dequant(x, Tensor(self.scales()), self.quant_bits)


FakeQuanterWithAbsMaxObserverLayer = FakeQuanterWithAbsMaxObserver


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

class QuantConfig:
    """config.py QuantConfig analog: maps layers/types -> (activation,
    weight) quanter factories."""

    def __init__(self, activation: Optional[BaseObserver] = None,
                 weight: Optional[BaseObserver] = None):
        self._default = (activation, weight)
        self._layer_cfg: Dict[int, tuple] = {}
        self._type_cfg: Dict[type, tuple] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def _config_for(self, layer):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._default != (None, None):
            from ..nn.common import Linear
            from ..nn.conv import Conv2D
            if isinstance(layer, (Linear, Conv2D)):
                return self._default
        return None


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------

class QuantedLinear(Layer):
    """qat-swapped Linear: fake-quants activation + weight around matmul."""

    def __init__(self, base, act_quanter, wt_quanter):
        super().__init__()
        self._base = base
        self.weight = base.weight
        self.bias = base.bias
        self.activation_quanter = (act_quanter._instance()
                                   if act_quanter else None)
        self.weight_quanter = (wt_quanter._instance() if wt_quanter else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            if hasattr(self.activation_quanter, "quantize"):
                x = self.activation_quanter.quantize(x)
            else:
                self.activation_quanter.observe(x)
        w = self.weight
        if self.weight_quanter is not None:
            if hasattr(self.weight_quanter, "quantize"):
                w = self.weight_quanter.quantize(w)
            else:
                self.weight_quanter.observe(w)
        from ..ops.linalg import matmul
        out = matmul(x, w)
        if self.bias is not None:
            out = out + self.bias
        return out


class QuantedConv2D(Layer):
    """qat-swapped Conv2D."""

    def __init__(self, base, act_quanter, wt_quanter):
        super().__init__()
        self._base = base
        self.weight = base.weight
        self.bias = base.bias
        self.activation_quanter = (act_quanter._instance()
                                   if act_quanter else None)
        self.weight_quanter = (wt_quanter._instance() if wt_quanter else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            if hasattr(self.activation_quanter, "quantize"):
                x = self.activation_quanter.quantize(x)
            else:
                self.activation_quanter.observe(x)
        w = self.weight
        if self.weight_quanter is not None:
            if hasattr(self.weight_quanter, "quantize"):
                w = self.weight_quanter.quantize(w)
            else:
                self.weight_quanter.observe(w)
        b = self._base
        return F.conv2d(x, w, self.bias, stride=b.stride, padding=b.padding,
                        dilation=b.dilation, groups=b.groups,
                        data_format=b.data_format)


_SWAP = {}


def _swap_table():
    if not _SWAP:
        from ..nn.common import Linear
        from ..nn.conv import Conv2D
        _SWAP[Linear] = QuantedLinear
        _SWAP[Conv2D] = QuantedConv2D
    return _SWAP


def _walk_and_swap(model: Layer, config: QuantConfig, make):
    for name, child in list(model.named_children()):
        cfg = config._config_for(child)
        swapped = None
        if cfg is not None:
            for base_t, quant_t in _swap_table().items():
                if isinstance(child, base_t):
                    swapped = make(quant_t, child, cfg)
                    break
        if swapped is not None:
            model.add_sublayer(name, swapped)
        else:
            _walk_and_swap(child, config, make)
    return model


class QAT:
    """qat.py QAT analog: swap layers for fake-quantizing counterparts."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        return _walk_and_swap(model, self._config,
                             lambda qt, child, cfg: qt(child, cfg[0], cfg[1]))

    def convert(self, model: Layer, inplace=False) -> Layer:
        return convert(model, inplace=inplace)


class PTQ:
    """ptq.py PTQ analog: insert pure observers; calibrate by running eval
    batches; then ``convert`` bakes the collected scales."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        return _walk_and_swap(model, self._config,
                             lambda qt, child, cfg: qt(child, cfg[0], cfg[1]))

    def convert(self, model: Layer, inplace=False) -> Layer:
        return convert(model, inplace=inplace)


class _ConvertedLinear(Layer):
    """Inference-form layer: weights pre-quantized to int8 + scale, computed
    as dequantized float matmul (MXU path); serving exports (w_int8, scale)."""

    def __init__(self, qlayer: QuantedLinear):
        super().__init__()
        bits = (qlayer.weight_quanter.quant_bits
                if qlayer.weight_quanter else 8)
        qmax = float(2 ** (bits - 1) - 1)
        w = np.asarray(qlayer.weight._data)
        scale = (float(qlayer.weight_quanter.scales())
                 if qlayer.weight_quanter else float(np.abs(w).max() or 1.0))
        self.w_int8 = Tensor(np.clip(np.round(w / scale * qmax), -qmax,
                                     qmax).astype(np.int8))
        self.scale = float(scale)
        self._qmax = qmax
        self.bias = qlayer.bias
        self.act_scale = (float(qlayer.activation_quanter.scales())
                          if qlayer.activation_quanter else None)

    def forward(self, x):
        from ..ops.linalg import matmul
        w = self.w_int8.astype("float32") * (self.scale / self._qmax)
        out = matmul(x, w)
        if self.bias is not None:
            out = out + self.bias
        return out


def convert(model: Layer, inplace=False) -> Layer:
    """Bake observed scales into inference-form layers."""
    if not inplace:
        model = copy.deepcopy(model)

    def _walk(m):
        for name, child in list(m.named_children()):
            if isinstance(child, QuantedLinear):
                m.add_sublayer(name, _ConvertedLinear(child))
            else:
                _walk(child)
    _walk(model)
    return model


__all__ = ["QuantConfig", "QAT", "PTQ", "convert", "quant_dequant",
           "BaseObserver", "AbsmaxObserver", "EMAObserver", "HistObserver",
           "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer", "QuantedLinear",
           "QuantedConv2D"]


class BaseQuanter(BaseObserver):
    """ref paddle.quantization.BaseQuanter: the trainable-quanter base —
    same observe/scales protocol plus quantize()."""

    def quantize(self, x):
        raise NotImplementedError


def quanter(cls=None, **kwargs):
    """ref paddle.quantization.quanter decorator: register a quanter class
    (factory protocol used by QuantConfig)."""

    def deco(c):
        c._instance = classmethod(lambda k: k(**kwargs))
        return c

    return deco(cls) if cls is not None else deco


__all__ += ["BaseQuanter", "quanter"]
