"""Quantization framework (QAT + PTQ).

Reference: python/paddle/quantization — ``QuantConfig`` (config.py:
add_layer_config/add_type_config), ``QAT`` (qat.py: quantize -> swap layers
for quantized counterparts with fake quanters), ``PTQ`` (ptq.py: insert
observers, then convert), observers/quanters under observers/ + quanters/.

TPU-native: fake quantization is a quantize-dequantize pair emitted inline
(XLA fuses it into the surrounding matmul), and the straight-through
estimator is expressed as ``x + stop_gradient(q(x) - x)`` so the eager tape
differentiates it with no custom-grad machinery. int8 simulation keeps
tensors in float on the MXU — the TPU serving path consumes the scales.
"""
from __future__ import annotations

import copy
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.layer import Layer
from ..ops.registry import dispatch


# ---------------------------------------------------------------------------
# fake-quant primitives
# ---------------------------------------------------------------------------

def _fake_quant_ste(x, scale, bit_length=8):
    """Quantize-dequantize with straight-through gradient (pure jnp)."""
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    return x + jax.lax.stop_gradient(q - x)


def quant_dequant(x, scale, bit_length=8):
    """Public fake-quant op (tape-recorded; STE gradient)."""
    return dispatch(_fake_quant_ste, (x, scale), {"bit_length": bit_length},
                    op_name="fake_quant_dequant")


# ---------------------------------------------------------------------------
# observers / quanters (factory objects in the config, instances per layer)
# ---------------------------------------------------------------------------

class BaseObserver:
    """observers/base_observer.py analog: tracks a scale from data."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits

    def _instance(self):
        return copy.deepcopy(self)

    def observe(self, x: Tensor) -> None:
        raise NotImplementedError

    def scales(self) -> np.ndarray:
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """observers/abs_max.py analog: running max of |x|."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 0.0

    def observe(self, x):
        arr = np.asarray(x._data if isinstance(x, Tensor) else x)
        self._max = max(self._max, float(np.max(np.abs(arr), initial=0.0)))

    def scales(self):
        return np.float32(self._max if self._max > 0 else 1.0)


class ChannelAbsmaxObserver(BaseObserver):
    """Per-output-channel absmax (observers/channel_wise abs_max analog):
    one scale per ``axis`` slice. The serving-grade weight observer —
    per-tensor absmax lets one outlier column starve every other
    channel of int8 codes."""

    def __init__(self, quant_bits=8, axis=-1):
        super().__init__(quant_bits)
        self.axis = axis
        self._max = None

    def observe(self, x):
        arr = np.abs(np.asarray(x._data if isinstance(x, Tensor) else x))
        red = tuple(i for i in range(arr.ndim)
                    if i != (self.axis % arr.ndim))
        cur = arr.max(axis=red) if red else arr
        self._max = cur if self._max is None else np.maximum(self._max, cur)

    def scales(self):
        if self._max is None:
            return np.float32(1.0)
        return np.where(self._max > 0, self._max, 1.0).astype(np.float32)


class EMAObserver(BaseObserver):
    """Exponential-moving-average absmax (observers/ema.py analog)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._val = None

    def observe(self, x):
        arr = np.asarray(x._data if isinstance(x, Tensor) else x)
        cur = float(np.max(np.abs(arr), initial=0.0))
        if self._val is None:
            self._val = cur
        else:
            self._val = (self.moving_rate * self._val
                         + (1 - self.moving_rate) * cur)

    def scales(self):
        return np.float32(self._val if self._val else 1.0)


class HistObserver(BaseObserver):
    """Percentile-of-histogram observer (observers/hist.py analog)."""

    def __init__(self, quant_bits=8, bins_count=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self.percent = percent
        self._hist = None
        self._edges = None

    def observe(self, x):
        arr = np.abs(np.asarray(x._data if isinstance(x, Tensor) else x))
        hi = float(arr.max(initial=0.0))
        if self._hist is None or self._edges[-1] <= 1e-8:
            # An all-zero first batch pins the edges to the degenerate
            # [0, 1e-8] range; every later re-bin then collapses the
            # accumulated mass into bin 0 and the zero mass dominates
            # the percentile CDF (scales() returns ~1e-8 regardless of
            # the real data). Zero batches carry no range information,
            # so keep (re)initializing until the first nonzero batch
            # fixes the range.
            self._edges = np.linspace(0, max(hi, 1e-8), self.bins_count + 1)
            self._hist = np.histogram(arr, bins=self._edges)[0].astype(
                np.float64)
        else:
            if hi > self._edges[-1]:  # re-bin into a wider range
                new_edges = np.linspace(0, hi, self.bins_count + 1)
                centers = (self._edges[:-1] + self._edges[1:]) / 2
                new_hist = np.histogram(centers, bins=new_edges,
                                        weights=self._hist)[0]
                self._edges, self._hist = new_edges, new_hist
            self._hist += np.histogram(arr, bins=self._edges)[0]

    def scales(self):
        # degenerate edges mean only zero batches so far: no range
        # information, so report the neutral scale instead of ~1e-8
        if self._hist is None or self._hist.sum() == 0 \
                or self._edges[-1] <= 1e-8:
            return np.float32(1.0)
        cdf = np.cumsum(self._hist) / self._hist.sum()
        idx = int(np.searchsorted(cdf, self.percent))
        return np.float32(self._edges[min(idx + 1, len(self._edges) - 1)])


class FakeQuanterWithAbsMaxObserver(BaseObserver):
    """quanters/abs_max.py analog — QAT quanter: observes a moving absmax
    while fake-quantizing every forward."""

    def __init__(self, quant_bits=8, moving_rate=0.9, name=None, dtype=None):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._obs = EMAObserver(quant_bits, moving_rate)

    def observe(self, x):
        self._obs.observe(x)

    def scales(self):
        return self._obs.scales()

    def quantize(self, x: Tensor) -> Tensor:
        self.observe(x)
        return quant_dequant(x, Tensor(self.scales()), self.quant_bits)


FakeQuanterWithAbsMaxObserverLayer = FakeQuanterWithAbsMaxObserver


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

class QuantConfig:
    """config.py QuantConfig analog: maps layers/types -> (activation,
    weight) quanter factories."""

    def __init__(self, activation: Optional[BaseObserver] = None,
                 weight: Optional[BaseObserver] = None):
        self._default = (activation, weight)
        self._layer_cfg: Dict[int, tuple] = {}
        self._type_cfg: Dict[type, tuple] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def _config_for(self, layer):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._default != (None, None):
            from ..nn.common import Linear
            from ..nn.conv import Conv2D
            if isinstance(layer, (Linear, Conv2D)):
                return self._default
        return None


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------

class QuantedLinear(Layer):
    """qat-swapped Linear: fake-quants activation + weight around matmul."""

    def __init__(self, base, act_quanter, wt_quanter):
        super().__init__()
        self._base = base
        self.weight = base.weight
        self.bias = base.bias
        self.activation_quanter = (act_quanter._instance()
                                   if act_quanter else None)
        self.weight_quanter = (wt_quanter._instance() if wt_quanter else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            if hasattr(self.activation_quanter, "quantize"):
                x = self.activation_quanter.quantize(x)
            else:
                self.activation_quanter.observe(x)
        w = self.weight
        if self.weight_quanter is not None:
            if hasattr(self.weight_quanter, "quantize"):
                w = self.weight_quanter.quantize(w)
            else:
                self.weight_quanter.observe(w)
        from ..ops.linalg import matmul
        out = matmul(x, w)
        if self.bias is not None:
            out = out + self.bias
        return out


class QuantedConv2D(Layer):
    """qat-swapped Conv2D."""

    def __init__(self, base, act_quanter, wt_quanter):
        super().__init__()
        self._base = base
        self.weight = base.weight
        self.bias = base.bias
        self.activation_quanter = (act_quanter._instance()
                                   if act_quanter else None)
        self.weight_quanter = (wt_quanter._instance() if wt_quanter else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            if hasattr(self.activation_quanter, "quantize"):
                x = self.activation_quanter.quantize(x)
            else:
                self.activation_quanter.observe(x)
        w = self.weight
        if self.weight_quanter is not None:
            if hasattr(self.weight_quanter, "quantize"):
                w = self.weight_quanter.quantize(w)
            else:
                self.weight_quanter.observe(w)
        b = self._base
        return F.conv2d(x, w, self.bias, stride=b.stride, padding=b.padding,
                        dilation=b.dilation, groups=b.groups,
                        data_format=b.data_format)


_SWAP = {}


def _swap_table():
    if not _SWAP:
        from ..nn.common import Linear
        from ..nn.conv import Conv2D
        _SWAP[Linear] = QuantedLinear
        _SWAP[Conv2D] = QuantedConv2D
    return _SWAP


def _walk_and_swap(model: Layer, config: QuantConfig, make):
    for name, child in list(model.named_children()):
        cfg = config._config_for(child)
        swapped = None
        if cfg is not None:
            for base_t, quant_t in _swap_table().items():
                if isinstance(child, base_t):
                    swapped = make(quant_t, child, cfg)
                    break
        if swapped is not None:
            model.add_sublayer(name, swapped)
        else:
            _walk_and_swap(child, config, make)
    return model


class QAT:
    """qat.py QAT analog: swap layers for fake-quantizing counterparts."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        return _walk_and_swap(model, self._config,
                             lambda qt, child, cfg: qt(child, cfg[0], cfg[1]))

    def convert(self, model: Layer, inplace=False) -> Layer:
        return convert(model, inplace=inplace)


class PTQ:
    """ptq.py PTQ analog: insert pure observers; calibrate by running eval
    batches; then ``convert`` bakes the collected scales."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        return _walk_and_swap(model, self._config,
                             lambda qt, child, cfg: qt(child, cfg[0], cfg[1]))

    def convert(self, model: Layer, inplace=False) -> Layer:
        return convert(model, inplace=inplace)


class _ConvertedLinear(Layer):
    """Inference-form layer: weights pre-quantized to int8 + scale, computed
    as dequantized float matmul (MXU path); serving exports (w_int8, scale)."""

    def __init__(self, qlayer: QuantedLinear):
        super().__init__()
        bits = (qlayer.weight_quanter.quant_bits
                if qlayer.weight_quanter else 8)
        qmax = float(2 ** (bits - 1) - 1)
        w = np.asarray(qlayer.weight._data)
        scale = np.asarray(
            qlayer.weight_quanter.scales() if qlayer.weight_quanter
            else (np.abs(w).max() or 1.0), np.float32)
        scale = np.maximum(scale, 1e-12)
        self.w_int8 = Tensor(np.clip(np.round(w / scale * qmax), -qmax,
                                     qmax).astype(np.int8))
        # per-tensor scales stay a plain float (the historical export
        # contract); channel-wise observers hand back a [out] vector
        self.scale = float(scale) if scale.ndim == 0 else Tensor(scale)
        self._qmax = qmax
        self._dq = (self.scale / qmax if isinstance(self.scale, float)
                    else Tensor((scale / qmax).astype(np.float32)))
        self.bias = qlayer.bias
        self.act_scale = (float(qlayer.activation_quanter.scales())
                          if qlayer.activation_quanter else None)

    def forward(self, x):
        from ..ops.linalg import matmul
        # dequant AFTER the matmul: scales are per-tensor or per-OUTPUT-
        # channel, so (x @ w8) * s == x @ (w8 * s) exactly — and the
        # elementwise dequant shrinks from O(in*out) weight elements per
        # call to O(batch*out) accumulator elements. The named scope
        # feeds opprof's "quant" op-class attribution.
        with jax.named_scope("weight_dequant"):
            w = self.w_int8.astype("float32")
        out = matmul(x, w)
        with jax.named_scope("weight_dequant"):
            out = out * self._dq
        if self.bias is not None:
            out = out + self.bias
        return out


def convert(model: Layer, inplace=False) -> Layer:
    """Bake observed scales into inference-form layers."""
    if not inplace:
        model = copy.deepcopy(model)

    def _walk(m):
        for name, child in list(m.named_children()):
            if isinstance(child, QuantedLinear):
                m.add_sublayer(name, _ConvertedLinear(child))
            else:
                _walk(child)
    _walk(model)
    return model


def _quant_metrics():
    from ..observability.metrics import get_registry
    reg = get_registry()
    return (reg.counter("quant.layers_quantized",
                        "Linear layers serving int8 weights"),
            reg.counter("quant.layers_fallback",
                        "Linear layers kept fp (calibration error over "
                        "the bound)"),
            reg.counter("quant.weight_bytes_saved",
                        "parameter bytes removed by int8 serving weights"),
            reg.histogram("quant.layer_rel_err",
                          "per-layer output rel-error of int8 vs fp "
                          "weights on the calibration probe"))


def serving_quantize(model: Layer, err_bound: float = 0.02,
                     probe_batch: int = 8, seed: int = 0, mesh=None,
                     channelwise: bool = True,
                     inplace: bool = False) -> Layer:
    """int8 serving weights via the PTQ ``convert()`` scales, with a
    per-layer fp fallback.

    Every plain ``nn.Linear`` is converted to the inference-form
    :class:`_ConvertedLinear` (``w_int8`` + absmax scales — the exact
    layers ``PTQ(...).convert()`` bakes; ``channelwise=True`` observes
    with :class:`ChannelAbsmaxObserver` for per-output-channel scales,
    ``False`` keeps the per-tensor absmax), **unless** the
    layer's output on a seeded calibration probe deviates from the fp
    layer by more than ``err_bound`` — outlier-heavy layers then stay
    fp instead of silently degrading quality. The error is the max
    over output units of the relative L2 deviation (small units floored
    at 1% of the largest): whole-tensor norms would let one huge
    outlier column mask the starvation of every other unit, and plain
    weight-reconstruction error (~1/254 for absmax int8 always) never
    trips any bound — per-unit output error is what makes the fallback
    real.

    ``mesh``: an optional :class:`~paddle_tpu.distributed.mesh.MeshRuntime`
    — accepted layers get ``w_int8`` committed under
    ``mesh.serving_weight_spec`` (same column-parallel trailing-dim
    placement as the fp weights, so tensor-parallel serving stays
    token-exact).

    Returns the (copied unless ``inplace``) model; the decision record
    lives in ``model._serving_quant_report``:
    ``{"layers": {path: {"rel_err", "mae", "quantized"}},
    "layers_quantized", "layers_fallback", "bytes_saved",
    "err_bound"}``. ``quant.*`` metrics mirror the counts.
    """
    from ..nn.common import Linear
    if not inplace:
        model = copy.deepcopy(model)
    quant_c, fallback_c, bytes_c, err_h = _quant_metrics()
    report = {"layers": {}, "layers_quantized": 0, "layers_fallback": 0,
              "bytes_saved": 0, "err_bound": float(err_bound)}

    def _walk(m, prefix):
        for name, child in list(m.named_children()):
            path = f"{prefix}.{name}" if prefix else name
            # exact type: Linear subclasses may carry forward semantics
            # the converted layer would drop
            if type(child) is Linear:
                obs = (ChannelAbsmaxObserver() if channelwise
                       else AbsmaxObserver())
                q = QuantedLinear(child, None, obs)
                q.weight_quanter.observe(child.weight)
                conv = _ConvertedLinear(q)
                rng = np.random.RandomState(
                    (seed + zlib_crc(path)) % (2 ** 31))
                x = rng.randn(probe_batch, child.in_features).astype(
                    np.float32)
                w = np.asarray(child.weight._data, np.float32)
                sc = (np.asarray(conv.scale._data)
                      if isinstance(conv.scale, Tensor) else conv.scale)
                wdq = (np.asarray(conv.w_int8._data, np.float32)
                       * (sc / conv._qmax))
                ref, out = x @ w, x @ wdq
                coln = np.linalg.norm(ref, axis=0)
                floor = max(0.01 * float(coln.max(initial=0.0)), 1e-12)
                rel = float((np.linalg.norm(out - ref, axis=0)
                             / np.maximum(coln, floor)).max(initial=0.0))
                mae = float(np.abs(out - ref).mean())
                err_h.observe(rel)
                ok = rel <= err_bound
                report["layers"][path] = {"rel_err": rel, "mae": mae,
                                          "quantized": bool(ok)}
                if ok:
                    if mesh is not None:
                        w8 = np.asarray(conv.w_int8._data)
                        spec = mesh.serving_weight_spec(w8.shape, path)
                        conv.w_int8 = Tensor(mesh.place(w8, spec))
                    w_bytes = np.asarray(child.weight._data).nbytes
                    saved = w_bytes - np.asarray(conv.w_int8._data).nbytes
                    report["bytes_saved"] += int(saved)
                    report["layers_quantized"] += 1
                    quant_c.inc()
                    bytes_c.inc(max(int(saved), 0))
                    m.add_sublayer(name, conv)
                else:
                    report["layers_fallback"] += 1
                    fallback_c.inc()
            else:
                _walk(child, path)

    def zlib_crc(s):
        import zlib
        return zlib.crc32(s.encode()) & 0xFFFFFFFF

    _walk(model, "")
    model._serving_quant_report = report
    return model


__all__ = ["QuantConfig", "QAT", "PTQ", "convert", "serving_quantize",
           "quant_dequant",
           "BaseObserver", "AbsmaxObserver", "ChannelAbsmaxObserver",
           "EMAObserver", "HistObserver",
           "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer", "QuantedLinear",
           "QuantedConv2D"]


class BaseQuanter(BaseObserver):
    """ref paddle.quantization.BaseQuanter: the trainable-quanter base —
    same observe/scales protocol plus quantize()."""

    def quantize(self, x):
        raise NotImplementedError


def quanter(cls=None, **kwargs):
    """ref paddle.quantization.quanter decorator: register a quanter class
    (factory protocol used by QuantConfig)."""

    def deco(c):
        c._instance = classmethod(lambda k: k(**kwargs))
        return c

    return deco(cls) if cls is not None else deco


__all__ += ["BaseQuanter", "quanter"]
