"""LBFGS optimizer (python/paddle/optimizer/lbfgs.py:LBFGS).

Closure-based full-batch quasi-Newton: step(closure) re-evaluates the loss
as the line search probes points. The two-loop recursion and strong-Wolfe
line search run over ONE flattened parameter vector (a single fused XLA
elementwise chain per probe), matching the reference's flatten-params
design without its per-tensor python loops.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .optimizer import Optimizer


def _strong_wolfe(phi, phi0, dphi0, alpha0=1.0, c1=1e-4, c2=0.9,
                  max_iters=25):
    """Strong-Wolfe line search on the 1-D restriction phi(a) = f(x + a*d).

    phi(a) -> (value, slope). Returns (alpha, n_evals, value_at_alpha).
    Standard bracket + zoom (Nocedal & Wright alg. 3.5/3.6).
    """
    evals = 0

    def zoom(lo, hi, f_lo, g_lo, f_hi):
        nonlocal evals
        a_star, f_star = lo, f_lo
        for _ in range(max_iters):
            a = 0.5 * (lo + hi)
            f_a, g_a = phi(a)
            evals += 1
            if f_a > phi0 + c1 * a * dphi0 or f_a >= f_lo:
                hi, f_hi = a, f_a
            else:
                if abs(g_a) <= -c2 * dphi0:
                    return a, f_a
                if g_a * (hi - lo) >= 0:
                    hi, f_hi = lo, f_lo
                lo, f_lo, g_lo = a, f_a, g_a
                a_star, f_star = a, f_a
            if abs(hi - lo) < 1e-12:
                break
        return a_star, f_star

    a_prev, f_prev, g_prev = 0.0, phi0, dphi0
    a = alpha0
    for i in range(max_iters):
        f_a, g_a = phi(a)
        evals += 1
        if f_a > phi0 + c1 * a * dphi0 or (i > 0 and f_a >= f_prev):
            alpha, f_star = zoom(a_prev, a, f_prev, g_prev, f_a)
            return alpha, evals, f_star
        if abs(g_a) <= -c2 * dphi0:
            return a, evals, f_a
        if g_a >= 0:
            alpha, f_star = zoom(a, a_prev, f_a, g_a, f_prev)
            return alpha, evals, f_star
        a_prev, f_prev, g_prev = a, f_a, g_a
        a = 2.0 * a
    return a_prev, evals, f_prev


def two_loop_direction(g, s_hist, y_hist):
    """L-BFGS two-loop recursion: approximate -H @ g from curvature pairs."""
    q = g
    alphas = []
    for s, y in zip(reversed(s_hist), reversed(y_hist)):
        rho = 1.0 / jnp.dot(y, s)
        a = rho * jnp.dot(s, q)
        q = q - a * y
        alphas.append((a, rho))
    if s_hist:
        s, y = s_hist[-1], y_hist[-1]
        gamma = jnp.dot(s, y) / jnp.dot(y, y)
        q = gamma * q
    for (a, rho), (s, y) in zip(reversed(alphas), zip(s_hist, y_hist)):
        b = rho * jnp.dot(y, q)
        q = q + (a - b) * s
    return -q


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, False)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.line_search_fn = line_search_fn
        self._s_hist: list = []
        self._y_hist: list = []
        self._prev_flat = None
        self._prev_grad = None

    # -- flatten helpers ---------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list
                if getattr(p, "trainable", not p.stop_gradient)]

    def _flat(self):
        return jnp.concatenate(
            [p._data.astype(jnp.float32).reshape(-1) for p in self._params()])

    def _flat_grad(self):
        gs = []
        for p in self._params():
            if p.grad is None:
                gs.append(jnp.zeros(int(np.prod(p._data.shape)), jnp.float32))
            else:
                gs.append(p.grad._data.astype(jnp.float32).reshape(-1))
        return jnp.concatenate(gs)

    def _write_flat(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p._data.shape))
            p._set_data(flat[off:off + n].reshape(p._data.shape)
                        .astype(p.dtype))
            off += n

    # -- the closure-driven step ------------------------------------------
    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that recomputes "
                             "the loss")
        lr = self.get_lr()

        def eval_at(flat):
            self._write_flat(flat)
            for p in self._params():
                p.clear_grad()
            loss = closure()
            return float(loss), self._flat_grad()

        x = self._flat()
        f, g = eval_at(x)
        n_evals = 1
        for _ in range(self.max_iter):
            if float(jnp.abs(g).max()) <= self.tolerance_grad:
                break
            if self._prev_flat is not None:
                s = x - self._prev_flat
                y = g - self._prev_grad
                if float(jnp.dot(s, y)) > 1e-10:
                    self._s_hist.append(s)
                    self._y_hist.append(y)
                    if len(self._s_hist) > self.history_size:
                        self._s_hist.pop(0)
                        self._y_hist.pop(0)
            d = two_loop_direction(g, self._s_hist, self._y_hist)
            dphi0 = float(jnp.dot(g, d))
            if dphi0 >= 0:  # not a descent direction: reset history
                self._s_hist.clear()
                self._y_hist.clear()
                d = -g
                dphi0 = float(jnp.dot(g, d))
            self._prev_flat, self._prev_grad = x, g

            if self.line_search_fn == "strong_wolfe":
                cache = {}

                def phi(a):
                    fa, ga = eval_at(x + a * d)
                    cache[a] = (fa, ga)
                    return fa, float(jnp.dot(ga, d))

                alpha, evals, _ = _strong_wolfe(phi, f, dphi0, alpha0=lr)
                n_evals += evals
                x_new = x + alpha * d
                if alpha in cache:
                    f_new, g_new = cache[alpha]
                else:
                    f_new, g_new = eval_at(x_new)
                    n_evals += 1
            else:
                x_new = x + lr * d
                f_new, g_new = eval_at(x_new)
                n_evals += 1

            if float(jnp.abs(x_new - x).max()) <= self.tolerance_change or \
                    abs(f_new - f) <= self.tolerance_change:
                x, f, g = x_new, f_new, g_new
                break
            x, f, g = x_new, f_new, g_new
            if n_evals >= self.max_eval:
                break
        self._write_flat(x)
        self._step_count += 1
        return f

    def _state_names(self):
        return []

    def _create_accumulators_for(self, param):
        pass

    def _update(self, p, g, state, lr):  # pragma: no cover - closure path
        raise RuntimeError("LBFGS updates through step(closure)")


__all__ = ["LBFGS"]
