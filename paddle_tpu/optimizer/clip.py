"""Gradient clipping (python/paddle/nn/clip.py analog: ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). The hybrid-parallel global-norm variant
lives in distributed.fleet (hybrid_parallel_optimizer.py:44 analog)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params):
        for p in params:
            if p.grad is not None:
                p.grad = Tensor(jnp.clip(p.grad._data, self.min, self.max))

    def apply_to_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max)
                for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return (g.astype(jnp.float32) * factor).astype(g.dtype)

    def __call__(self, params):
        for p in params:
            if p.grad is not None:
                p.grad = Tensor(self._clip_one(p.grad._data))

    def apply_to_arrays(self, grads):
        return [None if g is None else self._clip_one(g) for g in grads]


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __call__(self, params):
        grads = [p.grad._data for p in params if p.grad is not None]
        if not grads:
            return
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        global_norm = jnp.sqrt(sq)
        factor = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        for p in params:
            if p.grad is not None:
                g = p.grad._data
                p.grad = Tensor((g.astype(jnp.float32) * factor).astype(g.dtype))

    # functional form for jitted paths
    def apply_to_arrays(self, grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in grads if g is not None)
        global_norm = jnp.sqrt(sq)
        factor = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        return [None if g is None else
                (g.astype(jnp.float32) * factor).astype(g.dtype) for g in grads]
