"""paddle_tpu.optimizer (python/paddle/optimizer analog)."""
from . import lr
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue)
from .optimizer import Optimizer
from .lbfgs import LBFGS
from .optimizers import (ASGD, SGD, Adadelta, Adagrad, Adam, Adamax, AdamW,
                         Lamb, Momentum, RMSProp, Rprop)
