"""Optimizer base class.

Analog of python/paddle/optimizer/optimizer.py: accumulator management
(_create_accumulators / _add_accumulator), lr scheduling, grad clip, and
multi_precision master weights (reference: multi_precision in adamw kernel,
phi/kernels/gpu/adamw_kernel.cu).

TPU design note: every optimizer exposes a *functional* update
`_update(param_array, grad_array, state_dict) -> (new_param, new_state)` that
is pure jax — so the same optimizer drives both the eager `step()` path and
fully-jitted train steps (where XLA fuses the whole update into one kernel).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..autograd import no_grad
from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, float):
            self._weight_decay = weight_decay
        else:
            self._weight_decay = weight_decay  # None or regularizer object
        # name -> {param_id -> jax array}
        self._accumulators: Dict[str, Dict[int, jax.Array]] = {}
        self._master_weights: Dict[int, jax.Array] = {}
        self._step_count = 0
        self._current_param = None  # set during step() for per-param policies

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = value

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, dtype=None):
        store = self._accumulators.setdefault(name, {})
        if id(param) not in store:
            store[id(param)] = jnp.full(param._data.shape, fill_value,
                                        dtype or jnp.float32)
        return store[id(param)]

    def _get_accumulator(self, name, param):
        return self._accumulators[name][id(param)]

    def _restore_state_placement(self, v):
        """Hook: distributed state sharding (ZeRO offload) re-pins updated
        accumulators to their host residence; identity by default.
        Patched by distributed._shard_states.shard_optimizer_states."""
        return v

    def _fetch_state_for_update(self, v):
        """Hook: ZeRO offload prefetches host-resident accumulators to
        device memory for the eager update (jit inserts the transfer
        itself); identity by default."""
        return v

    def _master_weight(self, param):
        if id(param) not in self._master_weights:
            self._master_weights[id(param)] = param._data.astype(jnp.float32)
        return self._master_weights[id(param)]

    # -- the functional core (overridden per optimizer) ---------------------
    def _create_accumulators_for(self, param):
        """Populate self._accumulators entries for one param."""
        raise NotImplementedError

    def _update(self, p, g, state, lr):
        """Pure update: (param_array, grad_array, state dict, lr) ->
        (new_param, new_state). Must be jax-pure (jit-safe)."""
        raise NotImplementedError

    def _state_names(self) -> List[str]:
        raise NotImplementedError

    # -- eager step ---------------------------------------------------------
    @no_grad()
    def step(self):
        from ..amp import debugging as _dbg
        _dbg.advance_step()  # drives TensorCheckerConfig debug_step windows
        lr = self.get_lr()
        params = [p for p in self._parameter_list
                  if p.trainable and p.grad is not None]
        if self._grad_clip is not None:
            self._grad_clip(params)
        for p in params:
            self._current_param = p
            self._create_accumulators_for(p)
            use_master = self._multi_precision and p.dtype != jnp.float32
            state = {name: self._fetch_state_for_update(
                         self._accumulators[name][id(p)])
                     for name in self._state_names()}
            pdata = self._master_weight(p) if use_master else p._data
            g = p.grad._data
            if g.dtype != pdata.dtype:
                g = g.astype(pdata.dtype)
            new_p, new_state = self._update(pdata, g, state, lr)
            if use_master:
                self._master_weights[id(p)] = new_p
                p._set_data(new_p.astype(p.dtype))
            else:
                p._set_data(new_p)
            for name, v in new_state.items():
                self._accumulators[name][id(p)] = \
                    self._restore_state_placement(v)
        self._current_param = None
        self._step_count += 1

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # -- state dict ---------------------------------------------------------
    def state_dict(self):
        sd = OrderedDict()
        name_of = {id(p): (p.name or f"param_{i}")
                   for i, p in enumerate(self._parameter_list)}
        for acc_name, store in self._accumulators.items():
            for pid, arr in store.items():
                sd[f"{name_of.get(pid, pid)}.{acc_name}"] = Tensor(arr)
        for pid, arr in self._master_weights.items():
            sd[f"{name_of.get(pid, pid)}.master_weight"] = Tensor(arr)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["@step"] = self._step_count
        return sd

    def set_state_dict(self, state_dict):
        name_of = {(p.name or f"param_{i}"): p
                   for i, p in enumerate(self._parameter_list)}
        self._step_count = int(state_dict.get("@step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for key, value in state_dict.items():
            if key in ("LR_Scheduler", "@step"):
                continue
            pname, acc_name = key.rsplit(".", 1)
            p = name_of.get(pname)
            if p is None:
                continue
            arr = value._data if isinstance(value, Tensor) else jnp.asarray(value)
            if acc_name == "master_weight":
                self._master_weights[id(p)] = arr
            else:
                self._accumulators.setdefault(acc_name, {})[id(p)] = arr

    # -- hooks for jitted training (used by paddle_tpu.jit.TrainStep) -------
    def _functional_states(self, params):
        """Return (state_pytree, apply_fn) for a fully-jitted train step."""
        for p in params:
            self._create_accumulators_for(p)
        states = [{name: self._accumulators[name][id(p)]
                   for name in self._state_names()} for p in params]
        return states

    def _apply_functional(self, params_data, grads_data, states, lr):
        new_params, new_states = [], []
        for pdata, g, st in zip(params_data, grads_data, states):
            if g is None:
                new_params.append(pdata)
                new_states.append(st)
                continue
            if g.dtype != pdata.dtype:
                g = g.astype(pdata.dtype)
            np_, ns = self._update(pdata, g, st, lr)
            new_params.append(np_)
            new_states.append(ns)
        return new_params, new_states
