"""Concrete optimizers (python/paddle/optimizer/{sgd,momentum,adam,adamw,...}.py).

Each defines the pure `_update` used by both eager step() and jitted train
steps; phi fused kernels (fused_adam, phi/kernels/fusion) are replaced by XLA
fusing the whole elementwise update chain.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _state_names(self):
        return []

    def _create_accumulators_for(self, param):
        pass

    def _update(self, p, g, state, lr):
        if isinstance(self._weight_decay, float):
            g = g + self._weight_decay * p
        return p - lr * g, {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _state_names(self):
        return ["velocity"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("velocity", param)

    def _update(self, p, g, state, lr):
        if isinstance(self._weight_decay, float):
            g = g + self._weight_decay * p
        v = self._momentum * state["velocity"].astype(g.dtype) + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _state_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("moment1", param)
        self._add_accumulator("moment2", param)
        store1 = self._accumulators.setdefault("beta1_pow", {})
        store2 = self._accumulators.setdefault("beta2_pow", {})
        if id(param) not in store1:
            store1[id(param)] = jnp.asarray(1.0, jnp.float32)
            store2[id(param)] = jnp.asarray(1.0, jnp.float32)

    def _decayed_grad(self, p, g):
        if isinstance(self._weight_decay, float):
            return g + self._weight_decay * p
        return g

    def _update(self, p, g, state, lr):
        g = self._decayed_grad(p, g)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"].astype(g.dtype) + (1 - b1) * g
        v = b2 * state["moment2"].astype(g.dtype) + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1p.astype(g.dtype))
        vhat = v / (1 - b2p.astype(g.dtype))
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p, {"moment1": m, "moment2": v,
                       "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name=name)
        self._coeff = weight_decay if isinstance(weight_decay, float) else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    @property
    def _no_decay(self):
        # base step() sets _current_param so the decay filter can see the name
        p = self._current_param
        if p is None or self._apply_decay_param_fun is None:
            return False
        return not self._apply_decay_param_fun(p.name or "")

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        decay = 0.0 if self._no_decay else self._coeff
        if self._use_pallas_update(p):
            from ..ops.pallas.fused_ops import adamw_pallas
            new_p, m, v = adamw_pallas(
                p, state["moment1"], state["moment2"], g,
                lr=lr, beta1=b1, beta2=b2, eps=eps, weight_decay=decay,
                beta1_pow=b1p, beta2_pow=b2p)
            # keep accumulator dtype identical to the XLA path so toggling
            # the flag / checkpoint round-trips don't flip state dtypes
            m = m.astype(state["moment1"].dtype)
            v = v.astype(state["moment2"].dtype)
            return new_p, {"moment1": m, "moment2": v,
                           "beta1_pow": b1p, "beta2_pow": b2p}
        m = b1 * state["moment1"].astype(g.dtype) + (1 - b1) * g
        v = b2 * state["moment2"].astype(g.dtype) + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1p.astype(g.dtype))
        vhat = v / (1 - b2p.astype(g.dtype))
        new_p = p * (1.0 - lr * decay) - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p, {"moment1": m, "moment2": v,
                       "beta1_pow": b1p, "beta2_pow": b2p}

    @staticmethod
    def _use_pallas_update(p) -> bool:
        from ..core.flags import get_flag
        from ..ops import pallas as _pl
        return bool(get_flag("FLAGS_use_pallas_adamw")) and _pl.on_tpu() \
            and p.size >= 1024


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._init_value = initial_accumulator_value

    def _state_names(self):
        return ["moment"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("moment", param, fill_value=self._init_value)

    def _update(self, p, g, state, lr):
        if isinstance(self._weight_decay, float):
            g = g + self._weight_decay * p
        mom = state["moment"].astype(g.dtype) + jnp.square(g)
        new_p = p - lr * g / (jnp.sqrt(mom) + self._epsilon)
        return new_p, {"moment": mom}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _state_names(self):
        return ["mean_square", "mean_grad", "momentum"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("mean_square", param)
        self._add_accumulator("mean_grad", param)
        self._add_accumulator("momentum", param)

    def _update(self, p, g, state, lr):
        if isinstance(self._weight_decay, float):
            g = g + self._weight_decay * p
        rho = self._rho
        ms = rho * state["mean_square"].astype(g.dtype) + (1 - rho) * jnp.square(g)
        if self._centered:
            mg = rho * state["mean_grad"].astype(g.dtype) + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"].astype(g.dtype) + lr * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._rho = rho

    def _state_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("avg_squared_grad", param)
        self._add_accumulator("avg_squared_update", param)

    def _update(self, p, g, state, lr):
        if isinstance(self._weight_decay, float):
            g = g + self._weight_decay * p
        rho, eps = self._rho, self._epsilon
        asg = rho * state["avg_squared_grad"].astype(g.dtype) + (1 - rho) * jnp.square(g)
        asu = state["avg_squared_update"].astype(g.dtype)
        update = -jnp.sqrt(asu + eps) / jnp.sqrt(asg + eps) * g
        asu = rho * asu + (1 - rho) * jnp.square(update)
        return p + lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _state_names(self):
        return ["moment", "inf_norm", "beta1_pow"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("moment", param)
        self._add_accumulator("inf_norm", param)
        store = self._accumulators.setdefault("beta1_pow", {})
        if id(param) not in store:
            store[id(param)] = jnp.asarray(1.0, jnp.float32)

    def _update(self, p, g, state, lr):
        if isinstance(self._weight_decay, float):
            g = g + self._weight_decay * p
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        m = b1 * state["moment"].astype(g.dtype) + (1 - b1) * g
        inf = jnp.maximum(b2 * state["inf_norm"].astype(g.dtype), jnp.abs(g) + eps)
        new_p = p - (lr / (1 - b1p.astype(g.dtype))) * m / inf
        return new_p, {"moment": m, "inf_norm": inf, "beta1_pow": b1p}


class Lamb(Optimizer):
    """LAMB (python/paddle/optimizer/lamb.py; ref kernel phi lamb_kernel)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _state_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("moment1", param)
        self._add_accumulator("moment2", param)
        s1 = self._accumulators.setdefault("beta1_pow", {})
        s2 = self._accumulators.setdefault("beta2_pow", {})
        if id(param) not in s1:
            s1[id(param)] = jnp.asarray(1.0, jnp.float32)
            s2[id(param)] = jnp.asarray(1.0, jnp.float32)

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m = b1 * state["moment1"].astype(g.dtype) + (1 - b1) * g
        v = b2 * state["moment2"].astype(g.dtype) + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1p.astype(g.dtype))
        vhat = v / (1 - b2p.astype(g.dtype))
        r = mhat / (jnp.sqrt(vhat) + eps) + self._lamb_wd * p
        w_norm = jnp.linalg.norm(p.reshape(-1))
        r_norm = jnp.linalg.norm(r.reshape(-1))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p - lr * trust * r
        return new_p, {"moment1": m, "moment2": v,
                       "beta1_pow": b1p, "beta2_pow": b2p}


class ASGD(Optimizer):
    """Stochastic Average Gradient (python/paddle/optimizer/asgd.py:29;
    kernel phi asgd_kernel): keeps the last ``batch_num`` per-batch gradients
    y_i and steps along their running sum d / min(m+1, n)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        if batch_num <= 0:
            raise ValueError("batch_num must be positive")
        self._n = int(batch_num)

    def _state_names(self):
        return ["d", "ys", "m"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("d", param)
        store = self._accumulators.setdefault("ys", {})
        if id(param) not in store:
            store[id(param)] = jnp.zeros((self._n,) + tuple(param._data.shape),
                                         jnp.float32)
        m = self._accumulators.setdefault("m", {})
        if id(param) not in m:
            m[id(param)] = jnp.asarray(0, jnp.int32)

    def _update(self, p, g, state, lr):
        wd = self._weight_decay if isinstance(self._weight_decay, float) else 0.0
        m = state["m"]
        i = (m % self._n).astype(jnp.int32)
        gf = g.astype(jnp.float32)
        d = state["d"].astype(jnp.float32) - state["ys"][i] + gf
        ys = state["ys"].at[i].set(gf)
        count = jnp.minimum(m + 1, self._n).astype(jnp.float32)
        step_dir = (d / count).astype(g.dtype) + wd * p
        return p - lr * step_dir, {"d": d, "ys": ys, "m": m + 1}


class Rprop(Optimizer):
    """Resilient backprop (python/paddle/optimizer/rprop.py; full-batch
    sign-based per-weight step sizes)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_minus, self._eta_plus = etas
        self._initial_lr = learning_rate if isinstance(learning_rate, float) \
            else 0.001

    def _state_names(self):
        return ["prev_grad", "lr_t"]

    def _create_accumulators_for(self, param):
        self._add_accumulator("prev_grad", param)
        store = self._accumulators.setdefault("lr_t", {})
        if id(param) not in store:
            store[id(param)] = jnp.full(param._data.shape, self._initial_lr,
                                        jnp.float32)
    def _update(self, p, g, state, lr):
        gf = g.astype(jnp.float32)
        sign = jnp.sign(gf * state["prev_grad"])
        lr_t = jnp.clip(
            jnp.where(sign > 0, state["lr_t"] * self._eta_plus,
                      jnp.where(sign < 0, state["lr_t"] * self._eta_minus,
                                state["lr_t"])),
            self._lr_min, self._lr_max)
        # on sign change the step is skipped and the stored grad zeroed
        g_eff = jnp.where(sign < 0, 0.0, gf)
        new_p = p - (lr_t * jnp.sign(g_eff)).astype(p.dtype)
        return new_p, {"prev_grad": g_eff, "lr_t": lr_t}
