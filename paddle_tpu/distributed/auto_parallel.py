"""Auto-parallel core: ProcessMesh, placements, DistTensor API.

Reference (SURVEY.md §2.10): ProcessMesh (phi/core/distributed/auto_parallel/
process_mesh.h), placements (placement_types.h — Shard/Replicate/Partial),
DistTensor (dist_tensor.h:39), SPMD rules (phi/infermeta/spmd_rules/, 70 files)
and the pairwise ReshardFunction registry.

TPU-native redesign: a DistTensor is simply a Tensor whose jax.Array carries a
NamedSharding over a jax.sharding.Mesh. SPMD inference and resharding collapse
into XLA's GSPMD propagation — every op in this framework lowers through jit,
so sharding annotations placed here flow through matmul/attention/etc. with the
compiler inserting the collectives over ICI. shard_tensor works both eagerly
(device_put) and under trace (with_sharding_constraint), mirroring
python/paddle/distributed/auto_parallel/api.py: shard_tensor:124, reshard:302,
dtensor_from_local:247.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.tensor import Tensor


# -- placements (placement_types.h analog) ----------------------------------

class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("R")


class Partial(Placement):
    """Pending-reduction placement. Under GSPMD this state is internal to the
    compiler; we accept it in APIs for parity and materialize (reduce) on
    reshard to Replicate/Shard."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("P", self.reduce_type))


# -- ProcessMesh (process_mesh.py:72 analog) --------------------------------

_DEFAULT_MESH: List[Optional["ProcessMesh"]] = [None]


class ProcessMesh:
    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 _jax_mesh: Optional[Mesh] = None):
        if _jax_mesh is not None:
            self._mesh = _jax_mesh
            self._ids = np.arange(np.prod(_jax_mesh.devices.shape)).reshape(
                _jax_mesh.devices.shape)
        else:
            arr = np.asarray(mesh)
            if dim_names is None:
                dim_names = [f"d{i}" for i in range(arr.ndim)]
            devices = np.asarray(jax.devices(), dtype=object)[arr.reshape(-1)]
            self._mesh = Mesh(devices.reshape(arr.shape), tuple(dim_names))
            self._ids = arr
        self._dim_names = tuple(self._mesh.axis_names)

    @property
    def shape(self):
        return list(self._mesh.devices.shape)

    @property
    def ndim(self):
        return self._mesh.devices.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    @property
    def jax_mesh(self) -> Mesh:
        return self._mesh

    def get_dim_size(self, name):
        return self._mesh.shape[name]

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh along one axis (used by fleet topology)."""
        axis = self._dim_names.index(dim_name)
        if index is None:
            return self
        ids = np.take(self._ids, index, axis=axis)
        names = [n for i, n in enumerate(self._dim_names) if i != axis]
        return ProcessMesh(ids, names or ["d0"])

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), self._dim_names))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def set_default_mesh(mesh: ProcessMesh):
    _DEFAULT_MESH[0] = mesh


def get_default_mesh() -> Optional[ProcessMesh]:
    return _DEFAULT_MESH[0]


def auto_parallel_mesh(shape=None, dim_names=None) -> ProcessMesh:
    """Build a mesh over all visible devices."""
    n = len(jax.devices())
    if shape is None:
        shape = [n]
        dim_names = dim_names or ["x"]
    return ProcessMesh(np.arange(n).reshape(shape), dim_names)


# -- placement <-> PartitionSpec --------------------------------------------

def _spec_from_placements(ndim: int, mesh: ProcessMesh,
                          placements: Sequence[Placement]) -> PartitionSpec:
    entries: List[Optional[object]] = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim % ndim
            name = mesh.dim_names[mesh_dim]
            if entries[d] is None:
                entries[d] = name
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (name,)
            else:
                entries[d] = (entries[d], name)
    return PartitionSpec(*entries)


def _placements_from_spec(spec: PartitionSpec, mesh: ProcessMesh, ndim: int):
    placements = [Replicate() for _ in mesh.dim_names]
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for name in names:
            placements[mesh.dim_names.index(name)] = Shard(tdim)
    return placements


# -- DistTensor API ---------------------------------------------------------

def shard_tensor(x, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None):
    """distributed.shard_tensor (auto_parallel/api.py:124).

    Eager: device_put onto the mesh with the NamedSharding.
    Traced: lax.with_sharding_constraint — the annotation GSPMD propagates.
    """
    t = x if isinstance(x, Tensor) else Tensor(x, dtype=dtype)
    spec = _spec_from_placements(t.ndim, mesh, placements)
    sharding = NamedSharding(mesh.jax_mesh, spec)
    if isinstance(t._data, jax.core.Tracer):
        new_data = jax.lax.with_sharding_constraint(t._data, sharding)
        out = Tensor(new_data, stop_gradient=t.stop_gradient)
        out._grad_node = t._grad_node
        out._grad_out_idx = t._grad_out_idx
    else:
        out = t
        out._data = jax.device_put(t._data, sharding)
    out._dist_attr = {"mesh": mesh, "placements": list(placements)}
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    return out


def reshard(x: Tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """distributed.reshard (api.py:302) — GSPMD/XLA moves the data."""
    has_partial_src = x._dist_attr and any(
        p.is_partial() for p in x._dist_attr["placements"])
    if has_partial_src:
        raise NotImplementedError(
            "eager reshard from Partial: wrap the computation in jit where "
            "GSPMD materializes partials automatically")
    # reshard returns a NEW tensor (api.py:302); shard_tensor is in-place
    new = Tensor(x._data, stop_gradient=x.stop_gradient)
    new._grad_node = x._grad_node
    new._grad_out_idx = x._grad_out_idx
    return shard_tensor(new, mesh, placements)


def dtensor_from_local(local_tensor, mesh: ProcessMesh, placements):
    """api.py:247 — assemble a global DistTensor from per-rank local shards.
    Single-controller: local tensors are globally-addressable; concatenate
    along the shard dims."""
    t = local_tensor if isinstance(local_tensor, Tensor) else Tensor(local_tensor)
    return shard_tensor(t, mesh, placements)


def dtensor_to_local(dist_tensor, mesh=None, placements=None):
    """Local shard of this process (addressable data)."""
    data = dist_tensor._data
    try:
        shards = data.addressable_shards
        return Tensor(shards[0].data)
    except Exception:
        return dist_tensor


def unshard_dtensor(dist_tensor):
    """Replicate (gather) a DistTensor back to a dense tensor."""
    mesh = dist_tensor._dist_attr["mesh"] if dist_tensor._dist_attr else None
    if mesh is None:
        return dist_tensor
    return shard_tensor(dist_tensor, mesh,
                        [Replicate()] * len(mesh.dim_names))


def shard_layer(layer, mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """distributed.shard_layer (api.py) — apply shard_fn(name, layer, mesh)
    to place every sublayer's params."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh_):
            for pname, p in list(sublayer._parameters.items()):
                sublayer._parameters[pname] = shard_tensor(
                    p, mesh_, [Replicate()] * len(mesh_.dim_names))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, mesh)
    return layer


def get_placements(t: Tensor):
    if t._dist_attr:
        return t._dist_attr["placements"]
    return None


def get_mesh(t: Tensor):
    if t._dist_attr:
        return t._dist_attr["mesh"]
    return None


class ShardDataloader:
    """auto_parallel/api.py shard_dataloader:1792 analog: wrap a DataLoader
    so every batch lands sharded over the mesh — batch dim over the data
    axes (shard_dims), everything else replicated. Single-controller: the
    loader yields GLOBAL batches; sharding is one device_put per field."""

    def __init__(self, dataloader, meshes, input_keys=None, shard_dims=None,
                 is_dataset_splitted=False):
        self._loader = dataloader
        if isinstance(meshes, (list, tuple)):
            if any(m != meshes[0] for m in meshes[1:]):
                raise NotImplementedError(
                    "per-input meshes (pipeline-stage dataloaders) are not "
                    "supported yet; pass one mesh")
            meshes = meshes[0]
        self._mesh = meshes
        if shard_dims is None:
            shard_dims = self._mesh.dim_names[0]
        self._shard_axes = [shard_dims] if isinstance(shard_dims, str) \
            else list(shard_dims)
        self._input_keys = set(input_keys) if input_keys else None
        # is_dataset_splitted=True: the loader yields this PROCESS's local
        # shard (DistributedBatchSampler-style) — assemble the global
        # DistTensor from it instead of resharding it as a global batch
        self._splitted = bool(is_dataset_splitted)

    def _placements(self):
        return [Shard(0) if name in self._shard_axes else Replicate()
                for name in self._mesh.dim_names]

    def _shard(self, t):
        if not isinstance(t, Tensor):
            return t
        if self._splitted:
            return dtensor_from_local(t, self._mesh, self._placements())
        return shard_tensor(t, self._mesh, self._placements())

    def _shard_tree(self, batch, key=None):
        if isinstance(batch, dict):
            return {k: self._shard_tree(v, key=k) for k, v in batch.items()}
        if isinstance(batch, (list, tuple)):
            return type(batch)(self._shard_tree(v, key=key) for v in batch)
        if self._input_keys is not None and key is not None and \
                key not in self._input_keys:
            return batch
        return self._shard(batch)

    def __iter__(self):
        for batch in self._loader:
            yield self._shard_tree(batch)

    def __len__(self):
        return len(self._loader)


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    """distributed.shard_dataloader (auto_parallel/api.py:1792)."""
    return ShardDataloader(dataloader, meshes, input_keys=input_keys,
                           shard_dims=shard_dims,
                           is_dataset_splitted=is_dataset_splitted)
