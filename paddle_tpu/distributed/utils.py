"""paddle.distributed.utils (ref distributed/utils/__init__.py — empty
__all__; launch-time helpers live in distributed.launch)."""
__all__ = []
