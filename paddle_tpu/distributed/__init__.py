"""paddle_tpu.distributed — the distributed stack (python/paddle/distributed
analog, SURVEY.md §2.7-2.12).

Layers:
  collective.py   — eager collective API over XLA collectives (ICI/DCN)
  auto_parallel.py— ProcessMesh / placements / DistTensor over GSPMD
  parallel.py     — DataParallel
  sharding.py     — ZeRO stages as placement policies
  fleet/          — hybrid parallel: topology, TP layers, recompute, facade
"""
from __future__ import annotations

from .auto_parallel import (Partial, Placement, ProcessMesh, Replicate, Shard,
                            ShardDataloader, dtensor_from_local,
                            dtensor_to_local, get_mesh, get_placements,
                            reshard, shard_dataloader, shard_layer,
                            shard_tensor, unshard_dtensor)
from .collective import (Group, P2POp, ReduceOp, all_gather,
                         all_gather_object, all_reduce, alltoall, barrier,
                         batch_isend_irecv, broadcast, destroy_process_group,
                         get_rank, get_world_size, init_parallel_env, irecv,
                         is_initialized, isend, new_group, recv, reduce,
                         reduce_scatter, scatter, send, wait)
from ..core.native import TCPStore
from . import auto_tuner
from . import ps
from . import rpc
from .engine import DistModel, Strategy, to_static
from .parallel import DataParallel, sync_params_buffers
from . import fleet
from . import sharding as _sharding_mod
from .sharding import group_sharded_parallel, save_group_sharded_model

# convenience namespace paddle.distributed.sharding.*
sharding = _sharding_mod


def shard_optimizer(optimizer, mesh=None, shard_fn=None):
    """distributed.shard_optimizer (auto_parallel/api.py:_ShardOptimizer:552
    analog): shard optimizer states over the mesh's first axis."""
    from ._shard_states import shard_optimizer_states

    if mesh is None:
        from .fleet.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        mesh = hcg.mesh if hcg else init_parallel_env().mesh
    return shard_optimizer_states(optimizer, mesh, mesh.dim_names[0])


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn analog. Single-controller SPMD drives all
    devices from one process, so spawn degenerates to a direct call."""
    func(*args)


def get_group(gid=0):
    return init_parallel_env()


from . import checkpoint
from .checkpoint import load_state_dict, save_state_dict


# -- remaining python/paddle/distributed surface -----------------------------

from .collective import (ParallelMode, ReduceType, alltoall_single,  # noqa: E402
                         broadcast_object_list, gather, get_backend,
                         gloo_barrier, gloo_init_parallel_env, gloo_release,
                         get_bootstrap_store, is_available,
                         scatter_object_list)
from . import launch  # noqa: E402
from .watchdog import (CollectiveWatchdog, disable_collective_watchdog,  # noqa: E402
                       enable_collective_watchdog, get_watchdog,
                       reset_watchdog)
from ..framework import io  # noqa: E402  (paddle.distributed.io alias)


class ParallelEnv:
    """ref parallel.py ParallelEnv: env-derived rank/world info."""

    def __init__(self):
        import os
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.device_id = int(os.environ.get("FLAGS_selected_gpus", "0"))

    @property
    def local_rank(self):
        return self.rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.device_id


class DistAttr:
    """ref auto_parallel DistAttr: (mesh, placement-per-dim) descriptor."""

    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs or []

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"specs={self.sharding_specs})")


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """ref auto_parallel/api.py dtensor_from_fn: build then shard."""
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """ref distributed.split (fleet/layers/mpu collective_ops.split): build
    a row/column-parallel linear or vocab-parallel embedding on the current
    mp group. Maps onto the fleet mpu layers."""
    from .fleet.mp_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 0:
            layer = RowParallelLinear(in_f, out_f, has_bias=bias_attr
                                      is not False,
                                      input_is_parallel=not gather_out)
        else:
            layer = ColumnParallelLinear(in_f, out_f,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
    elif operation == "embedding":
        n_emb, dim = size
        layer = VocabParallelEmbedding(n_emb, dim)
    else:
        raise ValueError("operation must be 'linear' or 'embedding'")
    return layer(x)


# PS-mode dataset / entry configs (ref fluid PS datasets; document-only tier
# like the rest of the PS stack — see ps/__init__.py)
class _PSDatasetBase:
    def __init__(self, *args, **kwargs):
        self._files = []

    def set_filelist(self, files):
        self._files = list(files)

    def load_into_memory(self):
        pass

    def release_memory(self):
        pass


class InMemoryDataset(_PSDatasetBase):
    """ref distributed.InMemoryDataset (PS in-memory shuffle dataset):
    API-compatible stub — the PS training mode is out of TPU scope
    (SURVEY.md N17)."""


class QueueDataset(_PSDatasetBase):
    """ref distributed.QueueDataset: streaming PS dataset stub."""


class ProbabilityEntry:
    def __init__(self, probability):
        self.probability = probability


class CountFilterEntry:
    def __init__(self, count_filter):
        self.count_filter = count_filter


class ShowClickEntry:
    def __init__(self, show_name, click_name):
        self.show_name = show_name
        self.click_name = click_name
