"""paddle_tpu.distributed — the distributed stack (python/paddle/distributed
analog, SURVEY.md §2.7-2.12).

Layers:
  collective.py   — eager collective API over XLA collectives (ICI/DCN)
  auto_parallel.py— ProcessMesh / placements / DistTensor over GSPMD
  parallel.py     — DataParallel
  sharding.py     — ZeRO stages as placement policies
  fleet/          — hybrid parallel: topology, TP layers, recompute, facade
"""
from __future__ import annotations

from .auto_parallel import (Partial, Placement, ProcessMesh, Replicate, Shard,
                            ShardDataloader, dtensor_from_local,
                            dtensor_to_local, get_mesh, get_placements,
                            reshard, shard_dataloader, shard_layer,
                            shard_tensor, unshard_dtensor)
from .collective import (Group, P2POp, ReduceOp, all_gather,
                         all_gather_object, all_reduce, alltoall, barrier,
                         batch_isend_irecv, broadcast, destroy_process_group,
                         get_rank, get_world_size, init_parallel_env, irecv,
                         is_initialized, isend, new_group, recv, reduce,
                         reduce_scatter, scatter, send, wait)
from ..core.native import TCPStore
from . import auto_tuner
from . import ps
from . import rpc
from .engine import DistModel, Strategy, to_static
from .parallel import DataParallel, sync_params_buffers
from . import fleet
from . import sharding as _sharding_mod
from .sharding import group_sharded_parallel, save_group_sharded_model

# convenience namespace paddle.distributed.sharding.*
sharding = _sharding_mod


def shard_optimizer(optimizer, mesh=None, shard_fn=None):
    """distributed.shard_optimizer (auto_parallel/api.py:_ShardOptimizer:552
    analog): shard optimizer states over the mesh's first axis."""
    from ._shard_states import shard_optimizer_states

    if mesh is None:
        from .fleet.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        mesh = hcg.mesh if hcg else init_parallel_env().mesh
    return shard_optimizer_states(optimizer, mesh, mesh.dim_names[0])


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn analog. Single-controller SPMD drives all
    devices from one process, so spawn degenerates to a direct call."""
    func(*args)


def get_group(gid=0):
    return init_parallel_env()


from . import checkpoint
from .checkpoint import load_state_dict, save_state_dict
