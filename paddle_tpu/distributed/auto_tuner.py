"""Parallel-config auto tuner.

Reference: python/paddle/distributed/auto_tuner (tuner.py:21 AutoTuner —
candidate generation over dp/mp/pp/sharding/micro-batch space, prune
rules, history-guided search; trials launched as real runs).

TPU-native: the same search skeleton with an analytic TPU cost model as
the default evaluator (MXU-bound compute time + ICI collective time +
HBM capacity feasibility), and optional measured trials via a user-passed
``run_fn(config) -> metric``.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class TuneConfig:
    """Search space + model/hardware facts."""

    world_size: int = 8
    # model facts (defaults ~ Llama-7B)
    num_layers: int = 32
    hidden_size: int = 4096
    num_heads: int = 32
    vocab_size: int = 32000
    seq_length: int = 4096
    global_batch_size: int = 64
    dtype_bytes: int = 2           # bf16
    # hardware facts (defaults ~ v5e chip)
    hbm_bytes: float = 16e9
    flops_per_sec: float = 197e12  # bf16 MXU
    ici_bw_bytes: float = 4.5e10   # per-link, one direction
    # search space (None -> all divisors of world_size)
    dp_degree: Optional[List[int]] = None
    mp_degree: Optional[List[int]] = None
    pp_degree: Optional[List[int]] = None
    sharding_degree: Optional[List[int]] = None
    sharding_stage: List[int] = field(default_factory=lambda: [1, 2, 3])
    micro_batch_size: Optional[List[int]] = None


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class AutoTuner:
    """auto_tuner/tuner.py AutoTuner analog."""

    def __init__(self, config: TuneConfig,
                 run_fn: Optional[Callable[[Dict], float]] = None):
        self.cfg = config
        self.run_fn = run_fn
        self.history: List[Dict] = []

    # -- candidate generation + pruning (prune rules analog) ----------------
    def candidates(self) -> List[Dict]:
        c = self.cfg
        dps = c.dp_degree or _divisors(c.world_size)
        mps = c.mp_degree or _divisors(c.world_size)
        pps = c.pp_degree or _divisors(c.world_size)
        shs = c.sharding_degree or _divisors(c.world_size)
        mbs = c.micro_batch_size or _divisors(
            max(1, c.global_batch_size))
        out = []
        for dp, mp, pp, sh, stage, mb in itertools.product(
                dps, mps, pps, shs, c.sharding_stage, mbs):
            cand = {"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                    "sharding_degree": sh, "sharding_stage": stage,
                    "micro_batch_size": mb}
            if not self.prune(cand):
                out.append(cand)
        return out

    def prune(self, cand: Dict) -> bool:
        """True = discard. The reference's rule set adapted to TPU:
        degrees must tile the slice; mp must divide heads/hidden; batch
        must tile dp*micro; sharding rides the dp axis."""
        c = self.cfg
        dp, mp, pp = (cand["dp_degree"], cand["mp_degree"],
                      cand["pp_degree"])
        sh, mb = cand["sharding_degree"], cand["micro_batch_size"]
        if dp * mp * pp != c.world_size:
            return True
        if c.num_heads % mp or c.hidden_size % mp:
            return True
        if c.num_layers % pp:
            return True
        if sh > dp or dp % sh:
            return True  # sharding subdivides the dp axis
        if cand["sharding_stage"] > 1 and sh == 1:
            return True  # stage 2/3 need a sharding group
        per_dp_batch = c.global_batch_size // dp if \
            c.global_batch_size % dp == 0 else 0
        if per_dp_batch == 0 or per_dp_batch % mb:
            return True
        if not self._fits_memory(cand):
            return True
        return False

    # -- analytic model ------------------------------------------------------
    def _param_count(self) -> float:
        c = self.cfg
        per_layer = 12 * c.hidden_size ** 2  # qkvo + mlp(4h) roughly
        return c.num_layers * per_layer + c.vocab_size * c.hidden_size * 2

    def _fits_memory(self, cand) -> bool:
        c = self.cfg
        mp, pp, sh = (cand["mp_degree"], cand["pp_degree"],
                      cand["sharding_degree"])
        stage = cand["sharding_stage"]
        params = self._param_count() / mp / pp
        p_bytes = params * c.dtype_bytes
        # adam moments in fp32 + master weights
        opt_bytes = params * 12.0
        if stage >= 1:
            opt_bytes /= sh
        if stage >= 2:
            pass  # grads sharded too: transient, ignored here
        if stage >= 3:
            p_bytes /= sh
        act_bytes = (cand["micro_batch_size"] * c.seq_length * c.hidden_size
                     * c.dtype_bytes * c.num_layers / pp / mp
                     * 4)  # ~4 live activations/layer w/ remat
        return p_bytes + opt_bytes + act_bytes < c.hbm_bytes * 0.9

    def estimate(self, cand: Dict) -> float:
        """Predicted tokens/sec/chip (higher better)."""
        c = self.cfg
        mp, pp, dp = (cand["mp_degree"], cand["pp_degree"],
                      cand["dp_degree"])
        mb = cand["micro_batch_size"]
        tokens = mb * c.seq_length
        flops = 6 * self._param_count() * tokens  # fwd+bwd per micro-batch
        compute_t = flops / (c.flops_per_sec * mp * pp)
        # TP collectives: 4 allreduce of (tokens x hidden) per layer
        comm_bytes = (0 if mp == 1 else
                      4 * tokens * c.hidden_size * c.dtype_bytes
                      * c.num_layers / pp * 2 * (mp - 1) / mp)
        comm_t = comm_bytes / c.ici_bw_bytes
        # pipeline bubble factor
        micro_steps = max(1, c.global_batch_size // dp // mb)
        bubble = (pp - 1) / micro_steps if pp > 1 else 0.0
        step_t = (compute_t + comm_t) * (1 + bubble)
        return tokens / step_t / c.world_size * dp

    # -- search --------------------------------------------------------------
    def search(self, top_k: int = 1) -> List[Dict]:
        """Rank candidates by measured metric (run_fn) or the cost model."""
        scored = []
        for cand in self.candidates():
            metric = (self.run_fn(cand) if self.run_fn
                      else self.estimate(cand))
            entry = dict(cand, metric=metric)
            self.history.append(entry)
            scored.append(entry)
        scored.sort(key=lambda e: -e["metric"])
        return scored[:top_k]

    def best(self) -> Optional[Dict]:
        return max(self.history, key=lambda e: e["metric"], default=None)


__all__ = ["AutoTuner", "TuneConfig"]
