"""Fleet — hybrid-parallel facade.

Reference: fleet.init (distributed/fleet/fleet.py:167) builds the
HybridCommunicateGroup; fleet.distributed_model (fleet/model.py:32) wraps by
mode; fleet.distributed_optimizer returns HybridParallelOptimizer
(hybrid_parallel_optimizer.py:254).

TPU-native: the strategy's hybrid degrees define the device mesh axes
(dp, pp, sharding, sep, mp); "wrapping" a model = placing its parameters on
the mesh; the optimizer wrapper adds hybrid-aware clipping and (stage 1+)
sharded optimizer states. All collectives are GSPMD-emitted.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...optimizer.clip import ClipGradByGlobalNorm
from ..auto_parallel import Replicate, Shard, shard_tensor
from . import mp_layers, random_ctrl, recompute as _recompute_mod
from . import meta_parallel
from . import utils
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .pipeline_parallel import (PipelineParallel,
                                PipelineParallelWithInterleave)
from .segment_parallel import SegmentParallel
from .random_ctrl import get_rng_state_tracker
from .recompute import recompute, recompute_sequential
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_hybrid_communicate_group,
                       set_hybrid_communicate_group)


class HybridConfig(dict):
    pass


class DistributedStrategy:
    """fleet/base/distributed_strategy.py analog (proto
    framework/distributed_strategy.proto:359, HybridConfig:95)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.sharding = False
        self.sharding_configs = {}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel_configs = {}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class _Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        """fleet.init (fleet.py:167 → _init_hybrid_parallel_env fleet.py:603)."""
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        dims = [hc.get("dp_degree", 1), hc.get("pp_degree", 1),
                hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
                hc.get("mp_degree", 1)]
        import jax
        n = len(jax.devices())
        specified = int(np.prod([d for d in dims if d > 0]))
        # -1 on dp means "fill remaining devices"
        if hc.get("dp_degree", 1) in (-1, 0) or specified != n:
            fixed = int(np.prod(dims[1:]))
            dims[0] = max(n // fixed, 1)
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"], dims)
        self._hcg = HybridCommunicateGroup(topo)
        self._initialized = True
        return self

    @property
    def worker_num(self):
        import jax
        return jax.process_count()

    def worker_index(self):
        import jax
        return jax.process_index()

    def is_first_worker(self):
        return self.worker_index() == 0

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        """fleet.distributed_model (fleet/model.py:32): wrap by mode
        (model.py:132-171). A PipelineLayer under pp>1 becomes
        PipelineParallel (interleaved variant when the layer was built with
        virtual stages); otherwise params are placed on the mesh. TP layers
        already annotate their own params; remaining params are replicated
        across all axes (DP/sharding placement of grads/states happens in the
        optimizer/TrainStep tier)."""
        if self._hcg is None:
            raise RuntimeError("call fleet.init first")
        if isinstance(model, PipelineLayer) and \
                self._hcg.get_pipe_parallel_world_size() > 1:
            if self._hcg.get_sep_parallel_world_size() > 1:
                raise NotImplementedError(
                    "pp_degree > 1 combined with sep_degree > 1 is not "
                    "supported yet; shard the sequence inside the stages via "
                    "ring_attention/sep_mesh instead")
            cls = (PipelineParallelWithInterleave
                   if model.get_num_virtual_stages() > 1 else PipelineParallel)
            wrapped = cls(model, self._hcg, self._strategy)
            wrapped._fleet_hcg = self._hcg
            return wrapped
        if self._hcg.get_sep_parallel_world_size() > 1:
            wrapped = SegmentParallel(model, self._hcg)
            wrapped._fleet_hcg = self._hcg
            return wrapped
        mesh = self._hcg.mesh
        repl = [Replicate()] * len(mesh.dim_names)
        for p in model.parameters():
            if p._dist_attr is None:
                shard_tensor(p, mesh, repl)
        model._fleet_hcg = self._hcg
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       strategy or self._strategy)


fleet = _Fleet()


# module-level API: fleet.init(...), fleet.distributed_model(...)
def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def worker_num():
    return fleet.worker_num


def worker_index():
    return fleet.worker_index()


class HybridParallelClipGrad:
    """Hybrid global-norm clip (hybrid_parallel_optimizer.py:44). Under the
    single-controller mesh the grads are global arrays, so the norm is already
    global — the cross-axis norm reduction of the reference is implicit."""

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params):
        return self._clip(params)

    def apply_to_arrays(self, grads):
        return self._clip.apply_to_arrays(grads)


class HybridParallelOptimizer:
    """hybrid_parallel_optimizer.py:254 analog: wraps the inner optimizer,
    upgrades global-norm clip to the hybrid-aware version, and applies
    sharding-stage placement of optimizer states."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy
        if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, self._hcg)
        if (self._hcg is not None
                and self._hcg.get_sharding_parallel_world_size() > 1):
            _shard_optimizer_states(optimizer, self._hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def __setattr__(self, name, value):
        # Writes to inner-optimizer attrs (e.g. _step_count from TrainStep)
        # must land on the inner optimizer, not shadow it on the wrapper.
        if name in ("_inner_opt", "_hcg", "_strategy") \
                or "_inner_opt" not in self.__dict__ \
                or not hasattr(self._inner_opt, name):
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner_opt, name, value)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad()

    def minimize(self, loss, **kwargs):
        self._inner_opt.minimize(loss, **kwargs)


def _shard_optimizer_states(optimizer, hcg):
    """ZeRO stage-1: optimizer states sharded over the 'sharding' axis
    (DygraphShardingOptimizer analog, dygraph_sharding_optimizer.py:48)."""
    from .._shard_states import shard_optimizer_states
    shard_optimizer_states(optimizer, hcg.mesh, hcg.sharding_axis)


# meta-parallel wrappers (fleet/meta_parallel analog; on TPU they are
# placement policies rather than communication wrappers)
class TensorParallel:
    def __new__(cls, model, hcg=None, **kwargs):
        return model


class ShardingParallel:
    def __new__(cls, model, hcg=None, **kwargs):
        return model


# -- fleet infra classes (ref fleet/__init__.py exports) ---------------------

Fleet = _Fleet


class Role:
    """ref fleet/base/role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self):
        import os
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    def worker_index(self):
        return self._rank

    def worker_num(self):
        return self._size

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self._rank == 0

    def role(self):
        return Role.WORKER


class PaddleCloudRoleMaker(RoleMakerBase):
    """ref PaddleCloudRoleMaker: roles from the launch env vars."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective


class UserDefinedRoleMaker(RoleMakerBase):
    """ref UserDefinedRoleMaker: explicit rank/size."""

    def __init__(self, is_collective=True, current_id=0, worker_num=1,
                 role=Role.WORKER, **kwargs):
        super().__init__()
        self._rank = current_id
        self._size = worker_num
        self._role = role

    def role(self):
        return self._role


class UtilBase:
    """ref fleet/base/util_factory.py UtilBase: rank-collective helpers for
    user code (all_reduce on python values, barriers, fs access)."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np
        arr = np.asarray(input)
        return arr  # single-controller: the value is already global

    def barrier(self, comm_world="worker"):
        from ..collective import barrier as _barrier
        try:
            _barrier()
        except Exception:
            pass

    def all_gather(self, input, comm_world="worker"):
        from ..collective import _world
        try:
            n = _world().nranks
        except Exception:
            n = 1
        return [input] * n

    def get_file_shard(self, files):
        import os
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        return files[rank::size]


class MultiSlotDataGenerator:
    """ref fleet MultiSlotDataGenerator (PS data pipeline): subclass
    implements generate_sample; run_from_stdin feeds the PS dataset. The
    PS training mode is documentation-only in the TPU build (SURVEY N17),
    but the generator protocol works standalone for data prep."""

    def generate_sample(self, line):
        raise NotImplementedError

    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            g = self.generate_sample(line)
            for sample in g():
                sys.stdout.write(self._format(sample) + "\n")

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            g = self.generate_sample(line)
            for sample in g():
                out.append(self._format(sample))
        return out


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)
