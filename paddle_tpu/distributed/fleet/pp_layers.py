"""Pipeline model segmentation.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc:56,
SharedLayerDesc:76, PipelineLayer:237 (segments a flat layer list across pp
ranks, supports seg_method "uniform"/"layer:Cls", shared weights between
stages, recompute intervals, and interleaved virtual stages).

TPU-native redesign: the single-controller program owns EVERY stage. A stage
is a contiguous segment of the layer list whose parameters are placed on that
stage's sub-mesh (the hybrid mesh sliced at pipe=stage). There is no per-rank
partial model build: placement — not process identity — is what localizes a
stage to its devices, and XLA's async dispatch pipelines stages that the host
issues back-to-back. Tensor-parallel layers inside a stage annotate over the
stage sub-mesh, so TP collectives ride the stage's own ICI ring.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Sequence

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ..auto_parallel import ProcessMesh, Replicate, Shard, shard_tensor
from . import topology as topo_mod
from .recompute import recompute as _recompute


class LayerDesc:
    """Lazy layer constructor (pp_layers.py:56)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("layer_func must be a paddle_tpu.nn.Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Layer whose weight is shared between stages (pp_layers.py:76), e.g.
    tied input embedding / LM head. Each holding stage gets its own copy; the
    copies receive summed gradients after each pipeline step (the analog of
    the reference's allreduce over the shared-comm group) and therefore stay
    numerically identical under the optimizer."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition len(layers) into num_parts segments (pp_layers.py seg logic)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self._uniform(n, self.num_parts)
        if self.method.startswith("layer:"):
            # segment on occurrences of a named layer class (the transformer
            # block), keeping pre/post layers attached to first/last stages
            cls_name = self.method.split(":", 1)[1]
            weights = [0] * n
            for i, d in enumerate(self.layers_desc):
                name = (d.layer_func.__name__ if isinstance(d, LayerDesc)
                        else d.__class__.__name__)
                if re.fullmatch(cls_name, name):
                    weights[i] = 1
            total = sum(weights)
            if total == 0:
                raise ValueError(
                    f"seg_method '{self.method}' matched no layers — check "
                    "the class name")
            if total % self.num_parts:
                raise ValueError(
                    f"number of {cls_name} layers ({total}) is not divisible "
                    f"by num_stages ({self.num_parts})")
            per = total // self.num_parts
            result = [0]
            seen = 0
            for i, w in enumerate(weights):
                if w and seen % per == 0 and len(result) < self.num_parts:
                    if seen:
                        result.append(i)
                seen += w
            result.append(n)
            while len(result) < self.num_parts + 1:
                result.insert(1, result[1])
            return result
        raise ValueError(f"unknown seg_method {self.method}")

    @staticmethod
    def _uniform(num_items, num_parts) -> List[int]:
        result = [0] * (num_parts + 1)
        base, extra = divmod(num_items, num_parts)
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + base + (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    """pp_layers.py:237 analog.

    layers: list of Layer / LayerDesc / SharedLayerDesc (a flat module list).
    num_stages: pipeline depth (defaults to the topology's pp degree).
    num_virtual_pipeline_stages: >1 enables interleaved (VPP) scheduling —
        the layer list is cut into num_stages*vpp chunks assigned round-robin
        (chunk c lives on stage c % num_stages), matching the reference's
        interleave semantics (pp_layers.py _interleave segmentation).
    loss_fn: optional callable(output, labels) used by train_batch.
    seg_method: "uniform" or "layer:ClassName".
    recompute_interval: re-materialize every k layers inside a stage.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        hcg = topo_mod.get_hybrid_communicate_group()
        if topology is not None:
            topo_stages = topology.get_dim("pipe")
            if num_stages is not None and num_stages != topo_stages:
                raise ValueError(
                    f"num_stages ({num_stages}) conflicts with topology's "
                    f"pipe degree ({topo_stages})")
            num_stages = topo_stages
        if num_stages is None:
            if hcg is None:
                raise ValueError("num_stages or an initialized fleet topology "
                                 "is required")
            num_stages = hcg.get_pipe_parallel_world_size()
        self._hcg = hcg
        self._num_stages = num_stages
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        # recompute_ctx: reference recompute_hybrid options; honored keys here:
        # preserve_rng_state (offload_* have no host-side analog under XLA)
        self._recompute_ctx = dict(recompute_ctx or {})
        self._vpp = num_virtual_pipeline_stages or 1
        if self._vpp > 1 and seg_method != "uniform":
            raise ValueError("interleave requires uniform segmentation")

        self._layers_desc = list(layers)
        num_chunks = num_stages * self._vpp
        self.segment_parts = SegmentLayers(
            self._layers_desc, num_chunks, seg_method).do_segment()

        # chunk k spans layers [parts[k], parts[k+1]) and lives on stage
        # k % num_stages (round-robin for interleave; identity when vpp==1)
        self._chunk_to_stage = [k % num_stages for k in range(num_chunks)]
        self._stage_meshes = self._build_stage_meshes()

        self._shared_groups: Dict[str, List[Layer]] = {}
        self._shared_attrs: Dict[str, str] = {}
        self._chunks: List[List] = []  # entries: (idx, layer-or-callable, desc)
        run_list = []
        for k in range(num_chunks):
            built = []
            for i in range(self.segment_parts[k], self.segment_parts[k + 1]):
                desc = self._layers_desc[i]
                layer = desc.build_layer() if isinstance(desc, LayerDesc) else desc
                if isinstance(desc, SharedLayerDesc):
                    self._shared_groups.setdefault(desc.layer_name, []).append(layer)
                    self._shared_attrs[desc.layer_name] = desc.shared_weight_attr
                self.add_sublayer(f"chunk_{k}_layer_{i}", layer)
                fwd = desc.forward_func if isinstance(desc, SharedLayerDesc) \
                    else None
                built.append((i, layer, fwd))
            self._chunks.append(built)
            run_list.extend(built)
        self._run_list = run_list
        # per-layer parameter lists, cached for the recompute trainability hint
        self._param_cache = {id(l): list(l.parameters())
                             for _, l, _ in run_list}
        self._place_stage_params()
        self._sync_shared_weights()

    # -- placement ----------------------------------------------------------
    def _build_stage_meshes(self) -> List[Optional[ProcessMesh]]:
        if self._hcg is None:
            return [None] * self._num_stages
        mesh = self._hcg.mesh
        pp_axis = self._hcg.pp_axis  # e.g. "pipe"
        return [mesh.get_mesh_with_dim(pp_axis, s)
                for s in range(self._num_stages)]

    def _place_stage_params(self):
        """Pin each chunk's parameters to its stage sub-mesh. A param already
        annotated over the full hybrid mesh (TP layers) keeps its non-pipe
        placements, re-expressed on the stage mesh."""
        if self._hcg is None:
            return
        pp_axis = self._hcg.pp_axis
        full_names = self._hcg.mesh.dim_names
        for k, built in enumerate(self._chunks):
            smesh = self._stage_meshes[self._chunk_to_stage[k]]
            for _, layer, _ in built:
                for p in layer.parameters():
                    if p._dist_attr is not None and \
                            p._dist_attr["mesh"].dim_names == full_names:
                        placements = [
                            pl for name, pl in zip(
                                full_names, p._dist_attr["placements"])
                            if name != pp_axis]
                    elif p._dist_attr is not None and \
                            p._dist_attr["mesh"].dim_names == smesh.dim_names:
                        placements = p._dist_attr["placements"]
                    else:
                        placements = [Replicate()] * len(smesh.dim_names)
                    shard_tensor(p, smesh, placements)

    # -- topology accessors (pp_layers API parity) --------------------------
    def get_num_stages(self):
        return self._num_stages

    def get_num_virtual_stages(self):
        return self._vpp

    def get_stage_mesh(self, stage: int):
        return self._stage_meshes[stage]

    def stage_of_chunk(self, chunk: int) -> int:
        return self._chunk_to_stage[chunk]

    @property
    def num_chunks(self):
        return len(self._chunks)

    # -- shared weights -----------------------------------------------------
    def _sync_shared_weights(self):
        """Initialize every copy of a shared weight to the first copy's value
        (the reference broadcasts from the owning rank at init)."""
        import jax
        for key, layers in self._shared_groups.items():
            attr = self._shared_attrs[key]
            src = getattr(layers[0], attr)
            for other in layers[1:]:
                dst = getattr(other, attr)
                dst._set_data(jax.device_put(src._data, dst._data.sharding))

    def shared_groups(self):
        return {k: (self._shared_attrs[k], v)
                for k, v in self._shared_groups.items()}

    # -- forward ------------------------------------------------------------
    @staticmethod
    def _apply(layer_fn, x):
        """Feed an activation to a layer; a tuple activation becomes
        positional args (the reference's multi-output chaining semantics)."""
        return layer_fn(*x) if isinstance(x, tuple) else layer_fn(x)

    def forward_chunk(self, x, chunk: int):
        """Run one chunk's layers (with recompute intervals)."""
        built = self._chunks[chunk]
        interval = self._recompute_interval
        i = 0
        while i < len(built):
            if interval > 0:
                seg = built[i:i + interval]
                funcs = [b[1] for b in seg]
                # cheap per-call trainability check over the cached param
                # lists — skips the generic closure probe on the hot path
                seg_params = [p for b in seg for p in self._param_cache[id(b[1])]]
                hint = any(not p.stop_gradient for p in seg_params)

                def run_seg(*inp, _funcs=funcs):
                    h = inp if len(inp) > 1 else inp[0]
                    for f in _funcs:
                        h = self._apply(f, h)
                    return h

                preserve = self._recompute_ctx.get("preserve_rng_state", True)
                args = x if isinstance(x, tuple) else (x,)
                x = _recompute(run_seg, *args, preserve_rng_state=preserve,
                               _trainable_hint=hint)
                i += len(seg)
            else:
                _, layer, fwd = built[i]
                if fwd is not None:
                    x = fwd(layer, *x) if isinstance(x, tuple) \
                        else fwd(layer, x)
                else:
                    x = self._apply(layer, x)
                i += 1
        return x

    def stage_input(self, x, stage: int, prev_stage: Optional[int]):
        """Move an activation (Tensor or tuple of Tensors) onto `stage`'s
        sub-mesh — the p2p hop between pipeline stages."""
        from .p2p_communication import transfer
        mesh = self._stage_meshes[stage]
        if mesh is None or prev_stage == stage:
            return x
        src = None if prev_stage is None else self._stage_meshes[prev_stage]
        if isinstance(x, (list, tuple)):
            return type(x)(transfer(e, mesh, src)
                           if isinstance(e, Tensor) else e for e in x)
        return transfer(x, mesh, src) if isinstance(x, Tensor) else x

    def forward(self, x, chunk_id=None):
        if chunk_id is not None:
            return self.forward_chunk(x, chunk_id)
        prev_stage: Optional[int] = None
        for k in range(len(self._chunks)):
            stage = self._chunk_to_stage[k]
            x = self.stage_input(x, stage, prev_stage)
            x = self.forward_chunk(x, k)
            prev_stage = stage
        return x
