"""Tensor-parallel (Megatron-style) layers.

Reference: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding:46,
ColumnParallelLinear:335, RowParallelLinear:542, ParallelCrossEntropy:743,
with PyLayer collectives in mpu/mp_ops.py and the TP RNG tracker
(mpu/random.py:34).

TPU-native redesign: instead of manual identity/allreduce/allgather PyLayers
around local matmuls, each layer creates its parameter SHARDED over the "mp"
mesh axis and annotates activations. GSPMD then emits exactly the Megatron
collectives (allreduce after row-parallel, allgather for gather_output, etc.)
over ICI — the mp_ops.py PyLayer zoo collapses into sharding constraints.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer import Layer
from ..auto_parallel import Replicate, Shard, shard_tensor
from .topology import get_hybrid_communicate_group


def _mp_placements(mesh, shard_dim: Optional[int]):
    """Placements over the hybrid mesh: Shard(dim) on the mp axis, Replicate
    elsewhere."""
    placements = []
    for name in mesh.dim_names:
        if name == "mp" and shard_dim is not None:
            placements.append(Shard(shard_dim))
        else:
            placements.append(Replicate())
    return placements


def _annotate(t: Tensor, shard_dim: Optional[int], mesh=None) -> Tensor:
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return t
    mesh = mesh or hcg.mesh
    return shard_tensor(t, mesh, _mp_placements(mesh, shard_dim))


def _pad_to_multiple(n: int, ws: int) -> int:
    """Megatron-style padded size: jax shardings need every sharded dim
    divisible by its mesh axis, so an uneven partition (e.g. vocab 130
    over mp=4) pads the PARAMETER to the next multiple — the reference
    instead computes a ragged last shard explicitly
    (fleet/layers/mpu/mp_layers.py:46); padding is the established
    Megatron-LM practice and what a static SPMD partitioner wants."""
    return -(-n // max(ws, 1)) * max(ws, 1)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:46).

    A vocab not divisible by mp is padded to the next multiple (the
    weight holds unused tail rows; lookups never reach them since ids
    are < num_embeddings)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        hcg = get_hybrid_communicate_group()
        self.world_size = hcg.get_model_parallel_world_size() if hcg else 1
        rows = _pad_to_multiple(num_embeddings, self.world_size)
        self.weight = self.create_parameter(
            [rows, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        if rows != num_embeddings:
            # Megatron practice: phantom vocab rows must be EXACTLY zero —
            # a tied lm-head matmul (emb.weight used directly as the
            # output projection) would otherwise leak softmax mass onto
            # padded vocab entries
            self.weight._set_data(
                self.weight._data.at[num_embeddings:].set(0))
        self._register_padded_param("weight", 0, num_embeddings)
        _annotate(self.weight, 0)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over mp (mp_layers.py:335).
    weight [in, out] -> Shard(1); bias sharded alike. gather_output=False
    leaves activations sharded on the feature dim (annotated)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        hcg = get_hybrid_communicate_group()
        self.world_size = hcg.get_model_parallel_world_size() if hcg else 1
        cols = _pad_to_multiple(out_features, self.world_size)
        if cols != out_features and not gather_output:
            # sharded-output mode hands downstream layers the raw shard;
            # a padded tail inside it would silently corrupt their math
            raise ValueError(
                f"out_features={out_features} is not divisible by the mp "
                f"degree {self.world_size}; uneven column parallelism "
                f"needs gather_output=True (the padded tail is sliced "
                f"off after the gather)")
        self._padded_out = cols
        self.weight = self.create_parameter(
            [in_features, cols], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if cols != out_features:
            # zero pad columns: output is sliced after the gather anyway,
            # but zeroing keeps saved/loaded checkpoints bit-identical
            # across mp degrees (pad-on-load fills zeros)
            self.weight._set_data(
                self.weight._data.at[:, out_features:].set(0))
        self._register_padded_param("weight", 1, out_features)
        _annotate(self.weight, 1)
        if has_bias:
            self.bias = self.create_parameter([cols], attr=None,
                                              is_bias=True)
            self._register_padded_param("bias", 0, out_features)
            _annotate(self.bias, 0)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep the feature dim sharded over mp
            out = _annotate(out, out.ndim - 1)
        elif self._padded_out != self.out_features:
            out = out[..., :self.out_features]
        return out


class RowParallelLinear(Layer):
    """Linear with in_features sharded over mp (mp_layers.py:542).
    weight [in, out] -> Shard(0); GSPMD inserts the partial-sum allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        hcg = get_hybrid_communicate_group()
        self.world_size = hcg.get_model_parallel_world_size() if hcg else 1
        rows = _pad_to_multiple(in_features, self.world_size)
        self._padded_in = rows
        self.weight = self.create_parameter(
            [rows, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if rows != in_features:
            # zero pad rows: they multiply the zero-padded activation
            # tail, and zeros keep checkpoints canonical across degrees
            self.weight._set_data(
                self.weight._data.at[in_features:].set(0))
        self._register_padded_param("weight", 0, in_features)
        _annotate(self.weight, 0)
        if has_bias:
            self.bias = self.create_parameter([out_features], attr=None,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self._padded_in != self.in_features:
            # zero-pad the contraction dim: pad rows of the weight are
            # multiplied by zeros, so the product is exact
            import paddle_tpu as paddle
            pad = paddle.zeros(list(x.shape[:-1])
                               + [self._padded_in - self.in_features],
                               dtype=x.dtype)
            x = paddle.concat([x, pad], axis=-1)
        if self.input_is_parallel:
            x = _annotate(x, x.ndim - 1)
        out = F.linear(x, self.weight, self.bias)
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over vocab-sharded logits (mp_layers.py:743). With GSPMD
    the softmax reductions over the sharded class dim lower to psums over mp;
    the dedicated vocab-parallel kernel is unnecessary."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
