"""Elastic training manager.

Reference: fleet/elastic/ — enable_elastic (__init__.py:30), launch_elastic
(:51), ElasticManager (manager.py:126): registers ranks in etcd
(manager.py:192-197), watches membership, decides scale-in/out, restarts
trainers through the CollectiveLauncher; fault-level (restart in place) vs
elastic-level (re-form at a new world size).

TPU-native: membership lives in the launcher's rank-0 HTTP KV (the etcd
analog, launch/controllers.py KVServer) keyed by job id; hosts heartbeat and
the manager re-forms the jax.distributed world when membership settles at a
different size. Scale units are HOSTS — a TPU slice's chip set per host is
fixed, so elasticity = host set changes over DCN.
"""
from __future__ import annotations

import time
from typing import List, Optional

from ...resilience.retry import RetryGiveUp, RetryPolicy
from ..launch.controllers import KVClient, Watcher

ELASTIC_EXIT_CODE = 101  # reference's elastic restart exit code


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """fleet/elastic/manager.py:126 analog."""

    def __init__(self, master_endpoint: str, job_id: str, rank: int,
                 np: int, min_np: Optional[int] = None,
                 max_np: Optional[int] = None, heartbeat_ttl: float = 30.0,
                 retry: Optional[RetryPolicy] = None):
        # the shared retry policy rides INSIDE the KVClient: every
        # membership request backs off through master blips instead of
        # propagating and killing the trainer
        self.client = KVClient(master_endpoint, retry=retry)
        self.job_id = job_id
        self.rank = rank
        self.np = np
        self.min_np = min_np or np
        self.max_np = max_np or np
        self.ttl = heartbeat_ttl
        self.enable = True
        self._prefix = f"elastic/{job_id}"
        self._endpoint: Optional[str] = None   # what we registered as
        self._master_was_down = False
        self._last_alive: List[int] = []
        self.reregistrations = 0

    # -- membership (manager.py:192-197 register path) ----------------------
    def register(self, endpoint: str):
        self._endpoint = endpoint
        self.client.put(f"{self._prefix}/nodes/{self.rank}", endpoint)
        self.heartbeat()

    def deregister(self):
        self._endpoint = None
        self.client.delete(f"{self._prefix}/nodes/{self.rank}")

    def _reregister_if_lost(self):
        """A master that died and came back serves an EMPTY store: our
        nodes/<rank> key is gone even though this host never left. Put it
        back instead of letting the next scale decision read this rank as
        departed."""
        if self._endpoint is None:
            return
        if self.client.get(f"{self._prefix}/nodes/{self.rank}") is None:
            self.client.put(f"{self._prefix}/nodes/{self.rank}",
                            self._endpoint)
            self.reregistrations += 1
            from ...observability.metrics import get_registry
            get_registry().counter(
                "recoveries_total", "successful recovery actions, by kind",
                labelnames=("kind",)).labels(kind="reregister").inc()

    def heartbeat(self) -> bool:
        """Publish liveness; tolerate a down master (returns False — the
        beat thread keeps trying; registration is restored on the first
        beat that gets through after an outage)."""
        try:
            if self._master_was_down:
                self._master_was_down = False
                self._reregister_if_lost()
            self.client.put(f"{self._prefix}/heartbeat/{self.rank}",
                            str(time.time()))
            return True
        except (RetryGiveUp, OSError):
            self._master_was_down = True
            return False

    def alive_nodes(self) -> List[int]:
        now = time.time()
        alive = []
        try:
            kv = self.client.get_all()
        except (RetryGiveUp, OSError):
            # master unreachable: report the last observed membership —
            # an empty answer would read as "everyone died" and trigger a
            # pointless scale decision during a master restart
            return list(self._last_alive)
        for key, val in kv.items():
            if key.startswith(f"{self._prefix}/heartbeat/"):
                rank = int(key.rsplit("/", 1)[1])
                if now - float(val) <= self.ttl:
                    alive.append(rank)
        self._last_alive = sorted(alive)
        return self._last_alive

    # -- scale decisions (manager.py watch loop) ----------------------------
    def need_scale(self) -> bool:
        return len(self.alive_nodes()) != self.np

    def status(self) -> str:
        n = len(self.alive_nodes())
        if n == self.np:
            return ElasticStatus.HOLD
        if n < self.min_np:
            # below quorum: hold for peers to come back (fault level)
            return ElasticStatus.HOLD
        if n != self.np and self.min_np <= n <= self.max_np:
            return ElasticStatus.RESTART  # re-form at the new world size
        return ElasticStatus.EXIT

    def wait_for_np(self, np: Optional[int] = None,
                    timeout: float = 120.0) -> bool:
        """Block until `np` members are alive (manager.py wait path)."""
        want = np or self.np
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.alive_nodes()) >= want:
                return True
            self.heartbeat()
            time.sleep(0.2)
        return False


def enable_elastic(ctx, distribute_mode=None) -> bool:
    """fleet/elastic/__init__.py:30 analog: elastic is on when a master KV
    and a restart budget are configured."""
    return bool(getattr(ctx, "master", None)) and \
        int(getattr(ctx, "max_restarts", 0)) > 0


def launch_elastic(ctx, manager: Optional[ElasticManager] = None):
    """fleet/elastic/__init__.py:51 analog: run the trainer pod under the
    manager — register + heartbeat this host, restart the pod on elastic
    exits or membership changes (re-forming at the surviving world size),
    surface plain failures once the restart budget is spent.

    ctx: a launch.main.Context (the launcher builds it)."""
    import socket
    import threading

    from ..launch.controllers import CollectiveController

    if manager is None:
        manager = ElasticManager(ctx.master, ctx.job_id, ctx.node_rank,
                                 np=ctx.nnodes)
    manager.register(socket.gethostname())

    stop = threading.Event()

    def beat():
        while not stop.wait(manager.ttl / 3):
            try:
                manager.heartbeat()
            except Exception:  # noqa: BLE001 — master may be re-forming
                pass

    beater = threading.Thread(target=beat, daemon=True)
    beater.start()
    # THIS loop owns the restart budget: hand the controller a zero-restart
    # context so elastic exits surface immediately (the controller's own
    # retry loop would redeploy in place without the membership wait)
    import copy
    run_ctx = copy.copy(ctx)
    run_ctx.max_restarts = 0
    restarts = 0
    try:
        while True:
            controller = CollectiveController(run_ctx)
            # pod incarnation: restarted ranks must not read the previous
            # attempt's control-plane records (watchdog progress keys)
            controller.attempt = restarts
            controller.build_pod()
            code = controller.run()
            if code == 0:
                return 0
            if restarts >= ctx.max_restarts:
                return code
            restarts += 1
            elastic_exit = (code == ELASTIC_EXIT_CODE or manager.need_scale())
            from ..launch.controllers import announce_restart
            announce_restart(restarts, ctx.max_restarts, code,
                             elastic=elastic_exit)
            if not elastic_exit:
                # FAULT level (reference launch/controllers/collective.py
                # :272): a dead/hung trainer redeploys at the same
                # membership immediately
                continue
            # ELASTIC level: wait for membership, re-form at the surviving
            # world size — compact ranks and update the envs the next pod
            # will receive
            manager.wait_for_np(manager.min_np)
            alive = manager.alive_nodes()
            if manager.rank not in alive:
                alive = sorted(alive + [manager.rank])
            manager.np = len(alive)
            run_ctx.nnodes = len(alive)
            run_ctx.node_rank = alive.index(manager.rank)
            run_ctx.world_size = run_ctx.nnodes * run_ctx.nproc_per_node
    finally:
        stop.set()
        try:
            manager.deregister()
        except Exception:  # noqa: BLE001
            pass
