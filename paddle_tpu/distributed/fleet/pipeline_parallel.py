"""Pipeline-parallel schedules.

Reference: fleet/meta_parallel/pipeline_parallel.py — PipelineParallel:150
(1F1B, forward_backward_pipeline:440, train_batch:657),
PipelineParallelWithInterleave:906 (virtual-pipeline / VPP).

TPU-native redesign (single controller): the host issues forward/backward
work for every stage; XLA dispatch is asynchronous, so stage s's devices chew
on micro-batch m while stage s+1's devices run m-1 — the hardware overlap of
the reference's per-rank 1F1B emerges from dataflow, not from per-rank
programs. What the host-side 1F1B ORDER still controls is liveness: backward
of micro-batch m is issued right after warmup so its activations (vjp
residuals on the stage meshes) release early, bounding in-flight micro-batches
at num_stages like the reference instead of accumulate_steps like GPipe.

Interleave (VPP) differs from 1F1B only in placement here: chunks are assigned
round-robin (chunk c on stage c % num_stages, pp_layers segmentation), which
yields the reference's shallower per-stage model and its bubble profile; the
host issue order is unchanged because device queues, not issue order, schedule
the hardware.
"""
from __future__ import annotations

from typing import List, Optional

from ...core.tensor import Tensor
from ...nn.layer import Layer
from .p2p_communication import P2pHelper
from .pp_layers import PipelineLayer


def _split_micro(x, n: int):
    if isinstance(x, (list, tuple)):
        parts = [_split_micro(e, n) for e in x]
        return [tuple(p[i] for p in parts) for i in range(n)]
    if isinstance(x, Tensor):
        b = x.shape[0]
        if b % n:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {n}")
        step = b // n
        return [x[i * step:(i + 1) * step] for i in range(n)]
    return [x] * n


class PipelineParallel(Layer):
    """pipeline_parallel.py:150 analog."""

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", {}) or {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.num_stages = layers.get_num_stages()
        self._p2p = P2pHelper(layers._stage_meshes)
        self.total_loss = None

    # -- per-micro-batch units ---------------------------------------------
    def _forward_step(self, inp, label):
        """Run one micro-batch through all chunks; PipelineLayer.forward
        moves activations between stage meshes (_forward_step:732 analog)."""
        layers = self._layers
        if layers.num_chunks and layers._stage_meshes[0] is not None:
            self._p2p.meta.record(
                inp if isinstance(inp, (list, tuple)) else [inp])
        x = layers(inp)
        if layers._loss_fn is not None and label is not None:
            return layers._loss_fn(x, label)
        return x

    def _backward_step(self, loss, scaler):
        if scaler is not None:
            scaled = scaler.scale(loss)
            scaled.backward()
        else:
            loss.backward()

    # -- schedules ----------------------------------------------------------
    def forward_backward_pipeline(self, data, scaler=None,
                                  forward_only=False):
        """1F1B (forward_backward_pipeline:440 analog): warmup forwards for
        min(num_stages, m) micro-batches, then alternate B/F, then drain."""
        inputs, labels = data if isinstance(data, (list, tuple)) and \
            len(data) == 2 else (data, None)
        m = self.accumulate_steps
        micro_in = _split_micro(inputs, m)
        micro_lb = _split_micro(labels, m) if labels is not None else [None] * m

        inv = 1.0 / m
        losses: List[Tensor] = []
        pending: List[Tensor] = []  # forwarded, awaiting backward
        warmup = m if forward_only else min(self.num_stages, m)

        def fwd(i):
            out = self._forward_step(micro_in[i], micro_lb[i])
            if not forward_only and self._layers._loss_fn is not None:
                out = out * inv
            losses.append(out)
            pending.append(out)

        for i in range(warmup):
            fwd(i)
        if not forward_only:
            for i in range(m - warmup):
                self._backward_step(pending.pop(0), scaler)
                fwd(warmup + i)
            while pending:
                self._backward_step(pending.pop(0), scaler)
            self._sync_shared_grads()

        if self._layers._loss_fn is not None:
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            self.total_loss = total if not forward_only else total * inv
            return self.total_loss
        # no loss_fn: stitch the micro-batch outputs back into the full batch
        import paddle_tpu as paddle
        if isinstance(losses[0], tuple):
            return tuple(paddle.concat([o[i] for o in losses], axis=0)
                         for i in range(len(losses[0])))
        return paddle.concat(losses, axis=0) if len(losses) > 1 else losses[0]

    def _sync_shared_grads(self):
        """Sum gradients of shared-weight copies across their stages and
        write the sum to EVERY copy (the reference's unconditional allreduce
        over the shared comm group) so tied weights step identically even
        when only one copy saw a grad."""
        import jax
        for key, (attr, layers) in self._layers.shared_groups().items():
            params = [getattr(l, attr) for l in layers]
            grads = [p.grad for p in params if p.grad is not None]
            if not grads:
                continue
            total = grads[0]._data
            for g in grads[1:]:
                total = total + jax.device_put(g._data, total.sharding)
            for p in params:
                sh = p._data.sharding
                p.grad = Tensor(jax.device_put(total, sh))

    # -- public API (train_batch:657, eval_batch analogs) -------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler=scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ...autograd import no_grad
        with no_grad():
            if not compute_loss:
                saved, self._layers._loss_fn = self._layers._loss_fn, None
                try:
                    return self.forward_backward_pipeline(
                        data, forward_only=True)
                finally:
                    self._layers._loss_fn = saved
            return self.forward_backward_pipeline(data, forward_only=True)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """pipeline_parallel.py:906 analog. Placement (round-robin chunks) is done
    by PipelineLayer(num_virtual_pipeline_stages>1); the host order is shared
    with 1F1B — see module docstring for why that preserves VPP semantics."""

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if layers.get_num_virtual_stages() < 2:
            raise ValueError(
                "PipelineParallelWithInterleave requires a PipelineLayer built "
                "with num_virtual_pipeline_stages >= 2")
