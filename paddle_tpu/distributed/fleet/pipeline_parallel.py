"""Pipeline-parallel schedules.

Reference: fleet/meta_parallel/pipeline_parallel.py — PipelineParallel:150
(1F1B, forward_backward_pipeline:440, train_batch:657),
PipelineParallelWithInterleave:906 (virtual-pipeline / VPP).

TPU-native redesign (single controller): a schedule PLAN
(pipeline_schedules.generate_schedule — FThenB / 1F1B / interleaved VPP)
orders per-(chunk, micro) forward and backward UNITS; the executor walks
the plan with DETACHED stage boundaries, so each backward unit runs only
its own chunk's vjp and hands the boundary cotangent to the previous
chunk's unit — the per-rank p2p grad handoff of the reference
(pipeline_parallel.py:440, pp_utils/p2p_communication.py:313) becomes an
explicit cotangent dict. XLA dispatch is asynchronous, so stage s's devices
chew on micro-batch m while stage s+1's run m-1; the plan controls what
dispatch cannot: activation liveness (1F1B releases micro m's residuals
after ~num_stages micros, not accumulate_steps) and chunk interleaving
(VPP issues chunk-staggered forwards, pipeline_parallel.py:906).
"""
from __future__ import annotations

from typing import List, Optional

from ...autograd import no_grad
from ...core.tensor import Tensor
from ...nn.layer import Layer
from .p2p_communication import P2pHelper
from .pp_layers import PipelineLayer


def _split_micro(x, n: int):
    if isinstance(x, (list, tuple)):
        parts = [_split_micro(e, n) for e in x]
        return [tuple(p[i] for p in parts) for i in range(n)]
    if isinstance(x, Tensor):
        b = x.shape[0]
        if b % n:
            raise ValueError(f"batch {b} not divisible by accumulate_steps {n}")
        step = b // n
        return [x[i * step:(i + 1) * step] for i in range(n)]
    return [x] * n


class PipelineParallel(Layer):
    """pipeline_parallel.py:150 analog."""

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", {}) or {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.num_stages = layers.get_num_stages()
        self._p2p = P2pHelper(layers._stage_meshes)
        self.total_loss = None

    # -- schedule plan ------------------------------------------------------
    _schedule_kind = "1F1B"

    def _plan(self, num_micro, forward_only):
        from .pipeline_schedules import generate_schedule
        cfg = getattr(self._strategy, "pipeline_configs", {}) or {}
        kind = cfg.get("schedule_mode", self._schedule_kind)
        plan = generate_schedule(kind, self.num_stages,
                                 self._layers.num_chunks, num_micro)
        if forward_only:
            plan = [u for u in plan if u[0] == "F"]
        return list(plan)

    @staticmethod
    def _detach_boundary(x):
        """Cut the tape at a stage boundary: the chunk's backward then stops
        at its own input and the cotangent crosses by hand (the p2p analog)."""
        def cut(t):
            if isinstance(t, Tensor):
                d = t.detach()
                d.stop_gradient = False
                return d
            return t
        if isinstance(x, (list, tuple)):
            return type(x)(cut(e) for e in x)
        return cut(x)

    @staticmethod
    def _boundary_tensors(x):
        if isinstance(x, (list, tuple)):
            return [e for e in x if isinstance(e, Tensor)]
        return [x] if isinstance(x, Tensor) else []

    def forward_backward_pipeline(self, data, scaler=None,
                                  forward_only=False):
        """Plan-driven unit executor (forward_backward_pipeline:440 / :906
        analog): walks the FThenB/1F1B/VPP plan unit by unit, per-chunk
        backward via explicit cotangents across detached boundaries."""
        from ...autograd.engine import run_backward
        inputs, labels = data if isinstance(data, (list, tuple)) and \
            len(data) == 2 else (data, None)
        m = self.accumulate_steps
        micro_in = _split_micro(inputs, m)
        micro_lb = _split_micro(labels, m) if labels is not None else [None] * m

        layers = self._layers
        C = layers.num_chunks
        inv = 1.0 / m
        has_loss = layers._loss_fn is not None
        plan = self._plan(m, forward_only)
        self.schedule_trace = list(plan)

        acts = {}        # (chunk, micro) -> (boundary_in, out)
        cotangents = {}  # (chunk, micro) -> grads for chunk out's tensors
        outs: List = [None] * m

        for kind, c, mb in plan:
            stage = layers.stage_of_chunk(c)
            if kind == "F":
                if c == 0:
                    x = micro_in[mb]
                    if layers._stage_meshes[0] is not None:
                        self._p2p.meta.record(
                            x if isinstance(x, (list, tuple)) else [x])
                else:
                    # consume (and free) the producer's boundary activation
                    x = (acts.pop((c - 1, mb))[1] if forward_only
                         else acts[(c - 1, mb)][1])
                # the hop itself is not differentiated: the backward unit
                # hands the cotangent across by hand (no orphan tape nodes)
                with no_grad():
                    x = layers.stage_input(x, stage,
                                           layers.stage_of_chunk(c - 1)
                                           if c else None)
                if not forward_only:
                    x = self._detach_boundary(x)
                out = layers.forward_chunk(x, c)
                if c == C - 1 and has_loss and micro_lb[mb] is not None:
                    out = layers._loss_fn(out, micro_lb[mb])
                    if not forward_only:
                        out = out * inv
                    outs[mb] = out
                elif c == C - 1:
                    outs[mb] = out
                if not forward_only or c < C - 1:
                    acts[(c, mb)] = (x, out)
            else:  # backward unit
                x_in, out = acts.pop((c, mb))
                roots = self._boundary_tensors(out)
                if c == C - 1 and has_loss:
                    loss = out if scaler is None else scaler.scale(out)
                    run_backward([loss], [None])
                else:
                    grads = cotangents.pop((c, mb))
                    pairs = [(t, g) for t, g in zip(roots, grads)
                             if g is not None]
                    if pairs:
                        run_backward([t for t, _ in pairs],
                                     [g for _, g in pairs])
                if c > 0:
                    # hand the boundary cotangent to the previous chunk,
                    # hopping it onto that chunk's stage mesh (the reverse
                    # p2p of p2p_communication.py:313)
                    prev_stage = layers.stage_of_chunk(c - 1)
                    cotangents[(c - 1, mb)] = [
                        None if t.grad is None else layers.stage_input(
                            t.grad, prev_stage, stage)
                        for t in self._boundary_tensors(x_in)]

        if not forward_only:
            self._sync_shared_grads()

        if has_loss:
            total = outs[0]
            for l in outs[1:]:
                total = total + l
            self.total_loss = total if not forward_only else total * inv
            return self.total_loss
        # no loss_fn: stitch the micro-batch outputs back into the full batch
        import paddle_tpu as paddle
        if isinstance(outs[0], tuple):
            return tuple(paddle.concat([o[i] for o in outs], axis=0)
                         for i in range(len(outs[0])))
        return paddle.concat(outs, axis=0) if len(outs) > 1 else outs[0]

    def _sync_shared_grads(self):
        """Sum gradients of shared-weight copies across their stages and
        write the sum to EVERY copy (the reference's unconditional allreduce
        over the shared comm group) so tied weights step identically even
        when only one copy saw a grad."""
        import jax
        for key, (attr, layers) in self._layers.shared_groups().items():
            params = [getattr(l, attr) for l in layers]
            grads = [p.grad for p in params if p.grad is not None]
            if not grads:
                continue
            total = grads[0]._data
            for g in grads[1:]:
                total = total + jax.device_put(g._data, total.sharding)
            for p in params:
                sh = p._data.sharding
                p.grad = Tensor(jax.device_put(total, sh))

    # -- public API (train_batch:657, eval_batch analogs) -------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler=scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ...autograd import no_grad
        with no_grad():
            if not compute_loss:
                saved, self._layers._loss_fn = self._layers._loss_fn, None
                try:
                    return self.forward_backward_pipeline(
                        data, forward_only=True)
                finally:
                    self._layers._loss_fn = saved
            return self.forward_backward_pipeline(data, forward_only=True)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """pipeline_parallel.py:906 analog: round-robin chunk placement
    (PipelineLayer with num_virtual_pipeline_stages>1) PLUS the chunked-1F1B
    issue order — forwards of different chunks interleave across micros per
    the Megatron VPP warmup quota, shrinking the bubble relative to plain
    1F1B (see pipeline_schedules.generate_schedule)."""

    _schedule_kind = "VPP"

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if layers.get_num_virtual_stages() < 2:
            raise ValueError(
                "PipelineParallelWithInterleave requires a PipelineLayer built "
                "with num_virtual_pipeline_stages >= 2")
