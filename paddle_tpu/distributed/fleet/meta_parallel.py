"""fleet.meta_parallel namespace (reference: fleet/meta_parallel/__init__.py).

Re-exports the hybrid-parallel wrappers and pipeline building blocks under the
reference's import path: `from paddle.distributed.fleet.meta_parallel import
PipelineLayer, LayerDesc, ...`.
"""
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from .pipeline_parallel import (PipelineParallel,
                                PipelineParallelWithInterleave)
from .pp_layers import (LayerDesc, PipelineLayer, SegmentLayers,
                        SharedLayerDesc)
from .random_ctrl import RNGStatesTracker, get_rng_state_tracker
from .segment_parallel import SegmentParallel

__all__ = [
    "ColumnParallelLinear", "ParallelCrossEntropy", "RowParallelLinear",
    "VocabParallelEmbedding", "PipelineParallel",
    "PipelineParallelWithInterleave", "LayerDesc", "PipelineLayer",
    "SegmentLayers", "SharedLayerDesc", "RNGStatesTracker",
    "get_rng_state_tracker", "SegmentParallel",
]
