"""Hybrid-parallel topology.

Reference: CommunicateTopology + HybridCommunicateGroup
(fleet/base/topology.py:61,174) — axes ["dp","pp","sharding","sep","mp"] with
per-axis NCCL process groups (topology.py:344) and p2p prev/next rings.

TPU-native redesign: the topology IS a device mesh. One jax.sharding.Mesh with
named axes (dp, pp, sharding, sep, mp) backs every axis "group"; per-axis
collectives are XLA collectives over that axis name, and parallel layers
consume axis names rather than communicator handles.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import jax
import numpy as np

from ..auto_parallel import ProcessMesh
from ..collective import Group

_HCG: List[Optional["HybridCommunicateGroup"]] = [None]


class CommunicateTopology:
    """fleet/base/topology.py:61 analog."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(self._dims))
        self._coord_map = {}
        ranks = np.arange(self._world_size).reshape(self._dims)
        for coord in itertools.product(*[range(d) for d in self._dims]):
            self._coord_map[coord] = int(ranks[coord])
        self._rank_map = {v: k for k, v in self._coord_map.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord_map[coord]

    def get_coord(self, rank):
        return self._rank_map[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for r, c in self._rank_map.items() if c[axis] == index)

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name` (one per orthogonal coord)."""
        axis = self._parallel_names.index(axis_name)
        others = [list(range(d)) for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for coord in itertools.product(*others):
            group = []
            for k in range(self._dims[axis]):
                full = list(coord)
                full.insert(axis, k)
                group.append(self._coord_map[tuple(full)])
            groups.append(group)
        return groups


class HybridCommunicateGroup:
    """fleet/base/topology.py:174 analog — one mesh, five named axes."""

    AXIS_NAMES = {"data": "dp", "pipe": "pp", "sharding": "sharding",
                  "sep": "sep", "model": "mp"}

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        dims = topology._dims
        names = [self.AXIS_NAMES[n] for n in topology._parallel_names]
        n_dev = len(jax.devices())
        if topology.world_size() != n_dev:
            raise ValueError(
                f"topology world size {topology.world_size()} != device count "
                f"{n_dev}; on TPU every rank is a chip in the mesh")
        self.mesh = ProcessMesh(
            np.arange(n_dev).reshape(dims), names)
        self._groups: Dict[str, Group] = {}
        for pname, axis in self.AXIS_NAMES.items():
            ranks = topology.get_comm_list(pname)[0]
            self._groups[axis] = Group(ranks, self.mesh, axis)
        _HCG[0] = self

    # degree accessors (topology.py API parity)
    def get_data_parallel_world_size(self):
        return self._topo.get_dim("data")

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("model")

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pipe")

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    # single-controller: the logical program is "rank 0" on every axis
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_data_parallel_group(self) -> Group:
        return self._groups["dp"]

    def get_model_parallel_group(self) -> Group:
        return self._groups["mp"]

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pp"]

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sep_parallel_group(self) -> Group:
        return self._groups["sep"]

    def get_check_parallel_group(self):
        return self._groups["mp"]

    def topology(self):
        return self._topo

    # axis names for sharding annotations
    @property
    def dp_axis(self):
        return "dp"

    @property
    def mp_axis(self):
        return "mp"

    @property
    def pp_axis(self):
        return "pp"

    @property
    def sharding_axis(self):
        return "sharding"

    @property
    def sep_axis(self):
        return "sep"


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG[0]


def set_hybrid_communicate_group(hcg):
    _HCG[0] = hcg
