"""Pipeline p2p activation transfer.

Reference: fleet/meta_parallel/pp_utils/p2p_communication.py — SendRecvMeta:52
(shape/dtype handshake between adjacent ranks), _p2p_helper:313 (batched
isend/irecv on the pp group), plus four_directions_p2p_communication.py.

TPU-native redesign: the single controller addresses every stage's devices, so
"send/recv" is one jax.device_put from the source stage's sharding to the same
PartitionSpec on the destination stage's sub-mesh — an ICI (intra-slice) or
DCN (cross-slice) DMA issued asynchronously. There is no shape handshake over
a socket: the controller holds the metadata (SendRecvMeta is kept as a cache
for API parity and introspection). The transfer is autograd-aware: its vjp
moves the cotangent back onto the source mesh, which is exactly the reference's
backward p2p (send_backward/recv_backward).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...autograd import engine as _engine
from ...autograd.engine import GradNode
from ...core.tensor import Tensor
from ..auto_parallel import ProcessMesh


class SendRecvMeta:
    """Shape/dtype record per pipeline edge (p2p_communication.py:52 analog —
    here a controller-side cache, not a wire protocol)."""

    def __init__(self):
        self.send_shape_message = None
        self.send_dtype_message = None

    def record(self, tensors):
        ts = [t for t in (tensors if isinstance(tensors, (list, tuple))
                          else [tensors]) if isinstance(t, Tensor)]
        self.send_shape_message = [tuple(t.shape) for t in ts]
        self.send_dtype_message = [str(t.dtype) for t in ts]


def _activation_spec(arr) -> PartitionSpec:
    sh = getattr(arr, "sharding", None)
    if isinstance(sh, NamedSharding):
        return sh.spec
    return PartitionSpec()


def _put(arr, mesh: ProcessMesh, spec: PartitionSpec):
    return jax.device_put(arr, NamedSharding(mesh.jax_mesh, spec))


def transfer(x: Tensor, dst_mesh: Optional[ProcessMesh],
             src_mesh: Optional[ProcessMesh] = None) -> Tensor:
    """Move an activation onto the next stage's sub-mesh, keeping its
    PartitionSpec (dp/mp/sp shardings carry over — stage meshes share axis
    names). Differentiable: the cotangent rides back to the source mesh."""
    if dst_mesh is None:
        return x
    spec = _activation_spec(x._data)
    out_data = _put(x._data, dst_mesh, spec)

    requires = _engine.is_grad_enabled() and not x.stop_gradient
    out = Tensor(out_data, stop_gradient=not requires)
    if requires:
        back_mesh = src_mesh

        def vjp_fn(cts, _mesh=back_mesh, _spec=spec):
            ct = cts[0]
            if _mesh is None:
                return (ct,)
            return (_put(ct, _mesh, _spec),)

        node = GradNode("pipe_p2p", vjp_fn, [x], [True],
                        [(tuple(out.shape), out.dtype)])
        out._grad_node = node
        out._grad_out_idx = 0
    return out


class P2pHelper:
    """_p2p_helper:313 analog bound to a PipelineLayer's stage meshes."""

    def __init__(self, stage_meshes):
        self._meshes = stage_meshes
        self.meta = SendRecvMeta()

    def send_forward_recv_forward(self, x: Tensor, from_stage: int,
                                  to_stage: int) -> Tensor:
        self.meta.record(x)
        return transfer(x, self._meshes[to_stage], self._meshes[from_stage])

    # the reference's directional calls all collapse into `transfer`; kept as
    # named entry points for parity with p2p_communication.py
    def send_forward(self, x, from_stage, to_stage):
        return self.send_forward_recv_forward(x, from_stage, to_stage)

    def recv_forward(self, x, from_stage, to_stage):
        return self.send_forward_recv_forward(x, from_stage, to_stage)
