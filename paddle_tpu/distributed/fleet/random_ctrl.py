"""TP RNG state tracking (fleet/layers/mpu/random.py:34 RNGStatesTracker).

The reference keeps distinct CUDA RNG states per TP rank so dropout inside
TP regions differs across ranks while weight init matches. Single-controller
SPMD: there is one logical RNG; per-position randomness is already distinct
because the mask is drawn for the GLOBAL shape and sharded with the
activations. The tracker is kept for API parity and for explicitly-seeded
regions.
"""
from __future__ import annotations

import contextlib

import jax

from ...core import random as random_mod

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        gen = random_mod.default_generator()
        orig = gen.get_state()
        gen.set_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = gen.get_state()
            gen.set_state(orig)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import paddle_tpu as paddle
    seed = seed or 0
    global_seed = seed
    local_seed = seed + 1024
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    paddle.seed(global_seed)


def determinate_seed(rng_name):
    return 0
