"""fleet.utils namespace (reference: fleet/utils/__init__.py)."""
from . import sequence_parallel_utils
from .hybrid_parallel_util import (broadcast_dp_parameters,
                                   broadcast_mp_parameters,
                                   broadcast_sharding_parameters,
                                   fused_allreduce_gradients)
from .sequence_parallel_utils import (AllGatherOp, ColumnSequenceParallelLinear,
                                      GatherOp, ReduceScatterOp,
                                      RowSequenceParallelLinear, ScatterOp,
                                      is_sequence_parallel_parameter,
                                      mark_as_sequence_parallel_parameter,
                                      register_sequence_parallel_allreduce_hooks)

# -- reference fleet.utils __all__: LocalFS, HDFSClient, recompute,
#    DistributedInfer (fleet/utils/fs.py + __init__.py) ----------------------
from ..recompute import recompute  # noqa: E402
import os as _os  # noqa: E402
import shutil as _shutil  # noqa: E402


class LocalFS:
    """ref fleet/utils/fs.py LocalFS: filesystem ops behind the FS
    interface used by checkpoint/save paths."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(_os.listdir(fs_path)):
            (dirs if _os.path.isdir(_os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        _os.makedirs(fs_path, exist_ok=True)

    def is_exist(self, fs_path):
        return _os.path.exists(fs_path)

    def is_dir(self, fs_path):
        return _os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return _os.path.isfile(fs_path)

    def delete(self, fs_path):
        if _os.path.isdir(fs_path):
            _shutil.rmtree(fs_path, ignore_errors=True)
        elif _os.path.exists(fs_path):
            _os.remove(fs_path)

    def rename(self, src, dst):
        _os.rename(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if _os.path.exists(dst):
            if not overwrite:
                # os.rename would silently replace dst on POSIX — the
                # reference FS raises instead (checkpoint anti-clobber)
                raise FileExistsError(
                    f"mv: destination {dst} exists (overwrite=False)")
            self.delete(dst)
        _os.rename(src, dst)

    def upload(self, local_path, fs_path):
        _shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        _shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if _os.path.exists(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def cat(self, fs_path):
        with open(fs_path) as f:
            return f.read()

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """ref fleet/utils/fs.py HDFSClient (hadoop CLI wrapper): requires a
    hadoop binary; unavailable offline — raises with a clear message so
    checkpoint paths fall back to LocalFS."""

    def __init__(self, hadoop_home=None, configs=None, *a, **k):
        raise RuntimeError(
            "HDFSClient needs a hadoop installation; none exists in this "
            "environment — use LocalFS (same interface)")


class DistributedInfer:
    """ref fleet/utils/__init__.py DistributedInfer (PS inference helper):
    single-controller inference needs no var distribution; init/get
    methods keep API compatibility."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        return None

    def get_dist_infer_program(self):
        return self._main
