"""fleet.utils namespace (reference: fleet/utils/__init__.py)."""
from . import sequence_parallel_utils
from .hybrid_parallel_util import (broadcast_dp_parameters,
                                   broadcast_mp_parameters,
                                   broadcast_sharding_parameters,
                                   fused_allreduce_gradients)
from .sequence_parallel_utils import (AllGatherOp, ColumnSequenceParallelLinear,
                                      GatherOp, ReduceScatterOp,
                                      RowSequenceParallelLinear, ScatterOp,
                                      is_sequence_parallel_parameter,
                                      mark_as_sequence_parallel_parameter,
                                      register_sequence_parallel_allreduce_hooks)
