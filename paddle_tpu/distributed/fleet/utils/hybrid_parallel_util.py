"""Hybrid-parallel gradient/param sync helpers.

Reference: fleet/utils/hybrid_parallel_util.py — fused_allreduce_gradients
(bucketed grad allreduce over dp/sharding after backward),
broadcast_dp_parameters / broadcast_mp_parameters /
broadcast_sharding_parameters (param sync at wrap time).

TPU-native: with global jax.Arrays the tape's gradients already ARE the
cross-replica sums (GSPMD reduces over the batch-sharded dim in the matmul
transpose), and parameters placed Replicate over an axis are definitionally
identical across it — so these helpers validate/annotate rather than
communicate. They exist so reference training scripts port unchanged.
"""
from __future__ import annotations

from paddle_tpu.distributed.auto_parallel import Replicate, shard_tensor


def fused_allreduce_gradients(parameter_list, hcg=None):
    """hybrid_parallel_util.py fused_allreduce_gradients analog: grads of
    replicated params are already globally reduced under GSPMD; no-op."""
    return None


def _broadcast_params(model, hcg):
    """Place unannotated params Replicate over the full mesh (replication IS
    the broadcast invariant; axis distinctions have no effect here)."""
    if hcg is None:
        return model
    mesh = hcg.mesh
    for p in model.parameters():
        if p._dist_attr is None:
            shard_tensor(p, mesh, [Replicate()] * len(mesh.dim_names))
    return model


def broadcast_dp_parameters(model, hcg):
    return _broadcast_params(model, hcg)


def broadcast_mp_parameters(model, hcg):
    return _broadcast_params(model, hcg)


def broadcast_sharding_parameters(model, hcg):
    return _broadcast_params(model, hcg)
