"""Megatron-style sequence parallelism (SP inside the TP group).

Reference: fleet/utils/sequence_parallel_utils.py — ScatterOp/GatherOp/
AllGatherOp/ReduceScatterOp PyLayers (:84-136), ColumnSequenceParallelLinear
(:229), RowSequenceParallelLinear (:339), mark_as_sequence_parallel_parameter,
register_sequence_parallel_allreduce_hooks (:191).

TPU-native redesign: SP is a SHARDING of activations on the sequence dim over
the mp mesh axis, not a choreography of collectives. The scatter/gather
PyLayers become sharding annotations; GSPMD materializes exactly the
reference's reduce-scatter (after row-parallel matmul) and all-gather (before
column-parallel matmul) over ICI — including their transposes in backward.
Layout convention matches the reference: activations are [s, b, h] with the
sequence dim first.
"""
from __future__ import annotations

from typing import Optional

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.auto_parallel import (Replicate, Shard,
                                                  shard_tensor)
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer import Layer

from ..topology import get_hybrid_communicate_group


def _placements(mesh, axis_name, shard_dim: Optional[int]):
    return [Shard(shard_dim) if (name == axis_name and shard_dim is not None)
            else Replicate() for name in mesh.dim_names]


def _annotate_seq(t: Tensor, shard_dim: Optional[int]) -> Tensor:
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return t
    return shard_tensor(t, hcg.mesh,
                        _placements(hcg.mesh, hcg.mp_axis, shard_dim))


class ScatterOp:
    """sequence_parallel_utils.py:84 — split the sequence dim across the mp
    group. Here: annotate Shard(0) over the mp axis (GSPMD slices)."""

    @staticmethod
    def apply(x, axis=0):
        return _annotate_seq(x, axis)

    def __new__(cls, x, axis=0):
        return cls.apply(x, axis)


class GatherOp:
    """sequence_parallel_utils.py:104 — gather the sequence dim. Here:
    annotate Replicate over mp (GSPMD all-gathers)."""

    @staticmethod
    def apply(x, axis=0):
        return _annotate_seq(x, None)

    def __new__(cls, x, axis=0):
        return cls.apply(x, axis)


class AllGatherOp:
    """sequence_parallel_utils.py:118 — all-gather along seq (backward =
    reduce-scatter). Same annotation as GatherOp; GSPMD derives the backward
    collective from the sharding transpose."""

    @staticmethod
    def apply(x):
        return _annotate_seq(x, None)

    def __new__(cls, x):
        return cls.apply(x)


class ReduceScatterOp:
    """sequence_parallel_utils.py:136 — reduce partial sums and scatter along
    seq. In-graph the partial state is GSPMD-internal; annotating the output
    Shard(0) over mp after a row-parallel matmul yields the reduce-scatter."""

    @staticmethod
    def apply(x):
        return _annotate_seq(x, 0)

    def __new__(cls, x):
        return cls.apply(x)


# id -> weakref; id-keyed because Tensor's __eq__ is elementwise (set/dict
# membership on Tensors would build arrays), and Tensor is __slots__-only
_SP_PARAMS: dict = {}


def mark_as_sequence_parallel_parameter(parameter: Tensor):
    """sequence_parallel_utils.py marker: the reference must allreduce these
    params' grads over the mp group (their activations are seq-split). Under
    the global-array tape the gradient is already the full sum; the marker is
    kept for introspection/parity."""
    import weakref
    key = id(parameter)
    _SP_PARAMS[key] = weakref.ref(parameter,
                                  lambda _, k=key: _SP_PARAMS.pop(k, None))
    return parameter


def is_sequence_parallel_parameter(parameter: Tensor) -> bool:
    ref = _SP_PARAMS.get(id(parameter))
    return ref is not None and ref() is parameter


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """sequence_parallel_utils.py:191 analog. The reference registers grad
    hooks that allreduce marked params over mp; with global arrays + GSPMD the
    sum is produced by the compiler, so this is a checked no-op."""
    return layer


class ColumnSequenceParallelLinear(Layer):
    """sequence_parallel_utils.py:229 analog.

    Input arrives sequence-sharded [s/mp, b, h]; the reference all-gathers s
    then runs the column-parallel matmul. Here: weight Shard(1) over mp,
    output annotated feature-sharded — GSPMD all-gathers the input exactly
    once and keeps the output split on features for the next row layer."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        hcg = get_hybrid_communicate_group()
        self._hcg = hcg
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None
        if hcg is not None:
            shard_tensor(self.weight, hcg.mesh,
                         _placements(hcg.mesh, hcg.mp_axis, 1))
            if self.bias is not None:
                shard_tensor(self.bias, hcg.mesh,
                             _placements(hcg.mesh, hcg.mp_axis, 0))

    def forward(self, x):
        # x: [s(sharded over mp), b, in]; output feature-sharded
        out = F.linear(x, self.weight, self.bias)
        if self._hcg is not None and not self.gather_output:
            out = _annotate_seq(out, out.ndim - 1)
        return out


class RowSequenceParallelLinear(Layer):
    """sequence_parallel_utils.py:339 analog.

    Input is feature-sharded from the column layer; weight Shard(0) over mp.
    Annotating the output Shard(0) (sequence) makes GSPMD emit the
    reduce-scatter that replaces the reference's explicit ReduceScatterOp."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        hcg = get_hybrid_communicate_group()
        self._hcg = hcg
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None
        if hcg is not None:
            shard_tensor(self.weight, hcg.mesh,
                         _placements(hcg.mesh, hcg.mp_axis, 0))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self._hcg is not None:
            out = _annotate_seq(out, 0)  # sequence-sharded (reduce-scatter)
        return out
