"""Pipeline schedule PLANS: FThenB, 1F1B, interleaved VPP.

Reference: the static pass builds per-rank Job lists
(passes/pipeline_scheduler_pass.py — FThenB/1F1B/VPP plans run by the
multi-job StandaloneExecutor, new_executor/interpreter/plan.h), and the
dygraph schedules hand-code the same orders
(fleet/meta_parallel/pipeline_parallel.py:440 1F1B, :906 interleave).

TPU-native: a plan is a host-side issue ORDER over (F|B, chunk, micro)
units with detached stage boundaries. XLA's async dispatch turns the order
into device-level overlap, and each chunk's forward/backward compiles once
and is reused across micro-batches — the per-job programs of the reference
collapse into the executable cache. The plan still controls the two things
the compiler cannot: activation liveness (when a micro-batch's residuals
are released) and cross-chunk issue interleaving.

The generator SIMULATES the per-stage timeline round by round: each round,
every stage issues at most one ready unit, picked by the schedule's policy
(FThenB: all forwards first; 1F1B/VPP: forwards until the Megatron warmup
quota, then alternate, then drain). The emitted global order is the merged
timeline, so per-stage in-flight activations match the reference's bubble
profile instead of GPipe's O(num_micro).
"""
from __future__ import annotations

import functools
from typing import List, Tuple

Unit = Tuple[str, int, int]  # ("F"|"B", chunk, micro)


def _interleaved_order(S: int, V: int, M: int):
    """Megatron chunk-group cycling: micros advance in groups of up to S;
    within a group every local chunk runs before the next group starts.
    Handles M not divisible by S via a ragged final group."""
    order = []
    mb = 0
    while mb < M:
        grp = range(mb, min(mb + S, M))
        for chunk in range(V):
            for m in grp:
                order.append((chunk, m))
        mb += S
    return order


def _rank_program(kind: str, r: int, S: int, V: int, M: int) -> List[Unit]:
    """Stage r's per-rank unit sequence — the reference's per-rank job
    list. Global chunk ids: rank r owns chunks r, S+r, ..., so local chunk
    j maps to global j*S + r.

    1F1B/VPP follow the Megatron orders (classic warmup min(S-r-1, M) with
    forward-first steady state; interleaved warmup (S-r-1)*2 + (V-1)*S
    with chunk-group cycling — pipeline_parallel.py:440/:906); FThenB is
    all forwards then all backwards (pipeline_scheduler_pass.py FThenB
    plan).
    """
    total = M * V
    f_order = _interleaved_order(S, V, M)
    b_order = [(V - 1 - chunk, m) for chunk, m in f_order]

    def f_unit(k):
        chunk, micro = f_order[k]
        return ("F", chunk * S + r, micro)

    def b_unit(k):
        chunk, micro = b_order[k]
        return ("B", chunk * S + r, micro)

    if kind == "FThenB":
        return [f_unit(k) for k in range(total)] + \
               [b_unit(k) for k in range(total)]
    if V > 1:
        warm = min((S - r - 1) * 2 + (V - 1) * S, total)
    else:
        warm = min(S - r - 1, M)
    seq = [f_unit(k) for k in range(warm)]
    nf = warm
    nb = 0
    # steady state runs forward-first (Megatron order), then drains
    while nb < total:
        if nf < total:
            seq.append(f_unit(nf))
            nf += 1
        seq.append(b_unit(nb))
        nb += 1
    return seq


@functools.lru_cache(maxsize=64)
def generate_schedule(kind: str, num_stages: int, num_chunks: int,
                      num_micro: int) -> List[Unit]:
    """Global issue order for all (chunk, micro) forward+backward units:
    the per-rank programs merged on a simulated timeline (each round every
    stage runs its next program unit if its dependencies are done — the
    single-controller image of the reference's per-rank execution).

    Dependencies honored: F(c,m) after F(c-1,m); B(c,m) after F(c,m) and
    B(c+1,m). Memoized: the plan depends only on its four arguments, and
    generation is pure-Python — without the cache it would stall every
    train_batch.
    """
    if kind not in ("FThenB", "1F1B", "VPP"):
        raise ValueError(f"unknown pipeline schedule {kind!r}")
    S, C, M = num_stages, num_chunks, num_micro
    V = C // S
    if V > 1 and kind != "FThenB" and M % S:
        # Megatron's interleaved schedule carries the same constraint
        # (its assert: microbatches % pipeline-parallel size == 0); the
        # chunk-group cycling deadlocks on a ragged final group
        raise ValueError(
            f"interleaved pipeline schedules need accumulate_steps ({M}) "
            f"divisible by num_stages ({S})")
    progs = [_rank_program(kind, r, S, V, M) for r in range(S)]
    pc = [0] * S
    done = set()
    plan: List[Unit] = []
    total = 2 * C * M

    def ready(u):
        knd, c, m = u
        if knd == "F":
            return c == 0 or ("F", c - 1, m) in done
        return ("F", c, m) in done and (
            c == C - 1 or ("B", c + 1, m) in done)

    while len(plan) < total:
        progressed = False
        for r in range(S):
            if pc[r] < len(progs[r]) and ready(progs[r][pc[r]]):
                u = progs[r][pc[r]]
                pc[r] += 1
                done.add(u)
                plan.append(u)
                progressed = True
        if not progressed:
            raise RuntimeError(
                f"pipeline schedule deadlock in {kind} per-rank programs "
                f"(S={S}, C={C}, M={M}) — program order bug")
    return tuple(plan)


def validate_schedule(plan: List[Unit], num_chunks: int,
                      num_micro: int) -> None:
    """Assert the dependency order (used by tests; cheap enough for CI)."""
    done_f, done_b = set(), set()
    for kind, c, m in plan:
        if kind == "F":
            assert c == 0 or (c - 1, m) in done_f, f"F({c},{m}) too early"
            done_f.add((c, m))
        else:
            assert (c, m) in done_f, f"B({c},{m}) before its F"
            assert c == num_chunks - 1 or (c + 1, m) in done_b, \
                f"B({c},{m}) before B({c + 1},{m})"
            done_b.add((c, m))
    assert len(done_f) == len(done_b) == num_chunks * num_micro


def max_inflight_per_stage(plan: List[Unit], num_stages: int) -> List[int]:
    """Peak live (forwarded, not yet backwarded) units per stage — the
    activation-memory profile the schedule exists to bound."""
    live = [0] * num_stages
    peak = [0] * num_stages
    for kind, c, m in plan:
        s = c % num_stages
        if kind == "F":
            live[s] += 1
            peak[s] = max(peak[s], live[s])
        else:
            live[s] -= 1
    return peak
