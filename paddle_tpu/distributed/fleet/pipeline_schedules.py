"""Pipeline schedule PLANS: FThenB, 1F1B, interleaved VPP.

Reference: the static pass builds per-rank Job lists
(passes/pipeline_scheduler_pass.py — FThenB/1F1B/VPP plans run by the
multi-job StandaloneExecutor, new_executor/interpreter/plan.h), and the
dygraph schedules hand-code the same orders
(fleet/meta_parallel/pipeline_parallel.py:440 1F1B, :906 interleave).

TPU-native: a plan is a host-side issue ORDER over (F|B, chunk, micro)
units with detached stage boundaries. XLA's async dispatch turns the order
into device-level overlap, and each chunk's forward/backward compiles once
and is reused across micro-batches — the per-job programs of the reference
collapse into the executable cache. The plan still controls the two things
the compiler cannot: activation liveness (when a micro-batch's residuals
are released) and cross-chunk issue interleaving.

The generator SIMULATES the per-stage timeline round by round: each round,
every stage issues at most one ready unit, picked by the schedule's policy
(FThenB: all forwards first; 1F1B/VPP: forwards until the Megatron warmup
quota, then alternate, then drain). The emitted global order is the merged
timeline, so per-stage in-flight activations match the reference's bubble
profile instead of GPipe's O(num_micro).
"""
from __future__ import annotations

import functools
from typing import List, Tuple

Unit = Tuple[str, int, int]  # ("F"|"B", chunk, micro)


def warmup_quota(kind: str, num_stages: int, num_virtual: int,
                 num_micro: int) -> List[int]:
    """Per-stage forward-warmup quota before backwards interleave."""
    total = num_micro * num_virtual
    if kind == "FThenB":
        return [total] * num_stages
    if num_virtual == 1:  # classic 1F1B (pipeline_parallel.py:440)
        return [min(num_micro, num_stages - s) for s in range(num_stages)]
    # interleaved VPP (pipeline_parallel.py:906 / Megatron chunked 1F1B)
    return [min(total, (num_stages - s - 1) * 2 + (num_virtual - 1)
                * num_stages) for s in range(num_stages)]


@functools.lru_cache(maxsize=64)
def generate_schedule(kind: str, num_stages: int, num_chunks: int,
                      num_micro: int) -> List[Unit]:
    """Global issue order for all (chunk, micro) forward+backward units.

    Dependencies honored: F(c,m) after F(c-1,m); B(c,m) after F(c,m) and
    B(c+1,m). One unit per stage per round (stage = chunk % num_stages).
    Memoized: the plan depends only on its four arguments, and generation
    is pure-Python — without the cache it would stall every train_batch.
    """
    if kind not in ("FThenB", "1F1B", "VPP"):
        raise ValueError(f"unknown pipeline schedule {kind!r}")
    S, C, M = num_stages, num_chunks, num_micro
    V = C // S
    warm = warmup_quota(kind, S, V, M)

    done_f, done_b = set(), set()
    fcount = [0] * S
    plan: List[Unit] = []

    def f_ready(s):
        out = [(m, c) for c in range(s, C, S) for m in range(M)
               if (c, m) not in done_f
               and (c == 0 or (c - 1, m) in done_f)]
        return min(out) if out else None

    def b_ready(s):
        out = [(m, c) for c in range(s, C, S) for m in range(M)
               if (c, m) in done_f and (c, m) not in done_b
               and (c == C - 1 or (c + 1, m) in done_b)]
        return min(out) if out else None

    total = 2 * C * M
    while len(plan) < total:
        progressed = False
        for s in range(S):
            fr = f_ready(s)
            br = b_ready(s)
            pick = None
            if kind == "FThenB":
                pick = ("F", fr) if fr is not None else ("B", br)
            else:
                if fcount[s] < warm[s] and fr is not None:
                    pick = ("F", fr)
                elif br is not None:
                    pick = ("B", br)
                elif fr is not None:
                    pick = ("F", fr)
            if pick is None or pick[1] is None:
                continue
            knd, (m, c) = pick
            if knd == "F":
                done_f.add((c, m))
                fcount[s] += 1
            else:
                done_b.add((c, m))
            plan.append((knd, c, m))
            progressed = True
        if not progressed:  # safety: issue ANY globally ready unit
            for s in range(S):
                fr = f_ready(s)
                if fr is not None:
                    m, c = fr
                    done_f.add((c, m))
                    fcount[s] += 1
                    plan.append(("F", c, m))
                    progressed = True
                    break
            if not progressed:
                raise RuntimeError("pipeline schedule deadlock (bug)")
    return tuple(plan)


def validate_schedule(plan: List[Unit], num_chunks: int,
                      num_micro: int) -> None:
    """Assert the dependency order (used by tests; cheap enough for CI)."""
    done_f, done_b = set(), set()
    for kind, c, m in plan:
        if kind == "F":
            assert c == 0 or (c - 1, m) in done_f, f"F({c},{m}) too early"
            done_f.add((c, m))
        else:
            assert (c, m) in done_f, f"B({c},{m}) before its F"
            assert c == num_chunks - 1 or (c + 1, m) in done_b, \
                f"B({c},{m}) before B({c + 1},{m})"
            done_b.add((c, m))
    assert len(done_f) == len(done_b) == num_chunks * num_micro


def max_inflight_per_stage(plan: List[Unit], num_stages: int) -> List[int]:
    """Peak live (forwarded, not yet backwarded) units per stage — the
    activation-memory profile the schedule exists to bound."""
    live = [0] * num_stages
    peak = [0] * num_stages
    for kind, c, m in plan:
        s = c % num_stages
        if kind == "F":
            live[s] += 1
            peak[s] = max(peak[s], live[s])
        else:
            live[s] -= 1
    return peak
