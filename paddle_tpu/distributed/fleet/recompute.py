"""Activation recomputation (gradient checkpointing).

Reference: fleet/recompute/recompute.py — RecomputeFunction:108 (PyLayer that
stows inputs + RNG state, replays forward in backward), recompute:404,
recompute_sequential:542, and recompute_hybrid.py for the PP-aware variant.

TPU-native: the same stow-and-replay tape node. Under TrainStep/jit tracing
the replay unrolls into forward-without-residuals + recompute + backward, which
is exactly jax.checkpoint/remat semantics — XLA DCEs the unused first-pass
residuals, so compiled memory behavior matches the reference's.
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax

from ...autograd import engine as _engine
from ...autograd.engine import GradNode
from ...core import random as random_mod
from ...core.tensor import Tensor


_HARMLESS_TYPES = (str, bytes, int, float, bool, complex, type(None))


def _closure_requires_grad(function) -> bool:
    """Best-effort probe: does the callable's closure/bound self/referenced
    globals hold any trainable tensor? Used to skip taping fully frozen
    recompute regions. ANY object the probe cannot classify counts as
    trainable — a region is treated as frozen only when every piece of its
    reachable state is positively recognized as non-trainable."""
    import types

    import jax
    import numpy as np

    from ...nn.layer import Layer

    seen = set()

    def state_of(fn):
        """Objects reachable from a callable: partial args, bound self,
        closure cells, referenced globals."""
        out = []
        if isinstance(fn, functools.partial):
            out.extend(fn.args)
            out.extend(fn.keywords.values())
            fn = fn.func
        if getattr(fn, "__self__", None) is not None:
            out.append(fn.__self__)
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                out.append(cell.cell_contents)
            except ValueError:  # empty cell
                pass
        code = getattr(fn, "__code__", None)
        fglobals = getattr(fn, "__globals__", {})
        for name in (code.co_names if code is not None else ()):
            if name in fglobals:
                out.append(fglobals[name])
        return out

    def probe(obj, depth=0):
        """Returns True if obj may hold a trainable tensor (tape needed)."""
        if id(obj) in seen:
            return False
        seen.add(id(obj))
        if depth > 4:
            return True  # too deep to prove frozen
        if isinstance(obj, Layer):
            return any(not p.stop_gradient for p in obj.parameters())
        if isinstance(obj, Tensor):
            return not obj.stop_gradient
        if isinstance(obj, _HARMLESS_TYPES) or isinstance(
                obj, (np.ndarray, np.generic, jax.Array, types.ModuleType)):
            return False
        if isinstance(obj, (list, tuple, set, frozenset)):
            return any(probe(o, depth + 1) for o in obj)
        if isinstance(obj, dict):
            return any(probe(o, depth + 1) for o in obj.values())
        if isinstance(obj, (types.FunctionType, types.MethodType,
                            functools.partial)) or (
                callable(obj) and isinstance(obj, type)):
            if isinstance(obj, type):
                return False  # a class object, not an instance
            return any(probe(o, depth + 1) for o in state_of(obj))
        return True  # unrecognized object: cannot prove frozen

    return any(probe(o) for o in state_of(function)) if not isinstance(
        function, Layer) else probe(function)


def recompute(function, *args, **kwargs):
    """fleet.recompute analog (recompute.py:404). use_reentrant semantics of
    the reference's default (PyLayer) path."""
    kwargs.pop("use_reentrant", None)
    preserve_rng = kwargs.pop("preserve_rng_state", True)
    # callers that know their region's trainability (PipelineLayer caches its
    # segment param lists) can skip the generic closure probe
    trainable_hint = kwargs.pop("_trainable_hint", None)

    kw_keys = sorted(k for k, v in kwargs.items() if isinstance(v, Tensor))
    in_tensors = [a for a in args if isinstance(a, Tensor)] + \
        [kwargs[k] for k in kw_keys]
    # Record when any explicit input needs grad OR the function's closure
    # holds trainable parameters (the usual pipeline case: activations arrive
    # frozen but the segment's layers train — reference: RecomputeFunction is
    # a PyLayer whose backward accumulates into leaf params). Fully frozen
    # regions skip the tape entirely.
    requires = _engine.is_grad_enabled() and (
        any(not t.stop_gradient for t in in_tensors)
        or (trainable_hint if trainable_hint is not None
            else _closure_requires_grad(function)))

    gen = random_mod.default_generator()
    fwd_key = gen.get_state() if preserve_rng else None

    with _engine.no_grad():
        out = function(*args, **kwargs)

    if not requires:
        return out

    out_is_seq = isinstance(out, (list, tuple))
    out_list = [o for o in (out if out_is_seq else [out])
                if isinstance(o, Tensor)]
    out_avals = [(tuple(t.shape), t.dtype) for t in out_list]

    def vjp_fn(flat_cts):
        # replay forward WITH grad under the stashed RNG state
        saved_key = gen.get_state()
        saved_grads = [(t, t._grad) for t in in_tensors]
        try:
            if preserve_rng:
                gen.set_state(fwd_key)
            detached = []
            for a in args:
                if isinstance(a, Tensor):
                    d = Tensor(a._data, stop_gradient=a.stop_gradient)
                    detached.append(d)
                else:
                    detached.append(a)
            det_kwargs = dict(kwargs)
            for k in kw_keys:
                v = kwargs[k]
                det_kwargs[k] = Tensor(v._data, stop_gradient=v.stop_gradient)
            with _engine.enable_grad():
                re_out = function(*detached, **det_kwargs)
            re_list = [o for o in (re_out if isinstance(re_out, (list, tuple))
                                   else [re_out]) if isinstance(o, Tensor)]
            det_inputs = [d for d in detached if isinstance(d, Tensor)] + \
                [det_kwargs[k] for k in kw_keys]
            # Honor the OUTER sweep's leaf mode: under loss.backward()
            # (accumulate_leaf=True) closure params are replay-graph leaves
            # and their grads land directly on param.grad; under paddle.grad
            # (accumulate_leaf=False, no .grad mutation allowed) nothing is
            # accumulated, and grads for outer-requested tensors that only
            # appear inside this region (closure params) are routed back into
            # the outer sweep's result instead. Explicit inputs were detached,
            # so their grads ride up the outer tape as cotangents.
            octx = _engine.outer_backward_ctx()
            acc_leaf = octx["accumulate_leaf"] if octx else True
            outer_wanted = [t for t in (octx["inputs"] if octx else [])
                            if not any(t is d for d in in_tensors)]
            grads_map = _engine.run_backward(
                re_list, list(flat_cts),
                inputs=det_inputs + outer_wanted, accumulate_leaf=acc_leaf)
            if octx is not None:
                for t in outer_wanted:
                    if id(t) in grads_map:
                        octx["input_grads"][id(t)] = _engine._accum(
                            octx["input_grads"].get(id(t)), grads_map[id(t)])
            return tuple(grads_map.get(id(d)) for d in det_inputs)
        finally:
            gen.set_state(saved_key)
            for t, g in saved_grads:
                t._grad = g

    needs = [not t.stop_gradient for t in in_tensors]
    node = GradNode("recompute", vjp_fn, in_tensors, needs, out_avals)
    wrapped = []
    for idx, t in enumerate(out_list):
        nt = Tensor(t._data, stop_gradient=False)
        nt._grad_node = node
        nt._grad_out_idx = idx
        wrapped.append(nt)
    if out_is_seq:
        it = iter(wrapped)
        return type(out)(next(it) if isinstance(o, Tensor) else o for o in out)
    return wrapped[0]


def recompute_sequential(ctx, functions, *args, **kwargs):
    """recompute.py:542 analog — checkpoint a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    preserve = ctx.get("preserve_rng_state", True) if isinstance(ctx, dict) else True
    if hasattr(functions, "_sub_layers"):
        layers = list(functions._sub_layers.values())
    else:
        layers = list(functions)
    seg_size = max(len(layers) // max(segments, 1), 1)

    def run_segment(layers_seg):
        def fn(x):
            for l in layers_seg:
                x = l(x)
            return x
        return fn

    x = args[0]
    i = 0
    while i < len(layers):
        seg = layers[i:i + seg_size]
        x = recompute(run_segment(seg), x,
                      preserve_rng_state=preserve)
        i += seg_size
    return x
