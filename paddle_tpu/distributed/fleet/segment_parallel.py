"""SEP — segment (sequence-axis data) parallelism.

Reference: fleet/meta_parallel/segment_parallel.py:26 — SegmentParallel
wrapper; the "sep" topology axis splits the sequence dim of the inputs across
ranks while parameters are replicated (broadcast at init,
hybrid_parallel_util broadcast helpers).

TPU-native: inputs are annotated Shard(seq_dim) over the sep mesh axis;
parameters replicate over sep. Attention across the split sequence uses
ring_attention (paddle_tpu.ops.ring_attention) — the idiomatic TPU filler for
the reference's missing context parallelism (SURVEY.md §5): the reference's
SEP relies on attention kernels seeing the full sequence per rank, which a
sharded mesh axis cannot do; the ring supplies exact global attention with
neighbor-to-neighbor ICI traffic.
"""
from __future__ import annotations

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.auto_parallel import (Replicate, Shard,
                                                  shard_tensor)
from paddle_tpu.nn.layer import Layer

from .topology import get_hybrid_communicate_group


class SegmentParallel(Layer):
    """segment_parallel.py:26 analog."""

    def __init__(self, layers, hcg=None, seq_dim: int = 1, **kwargs):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        self._seq_dim = seq_dim
        if self._hcg is not None:
            mesh = self._hcg.mesh
            repl = [Replicate()] * len(mesh.dim_names)
            for p in layers.parameters():
                if p._dist_attr is None:
                    shard_tensor(p, mesh, repl)

    def _shard_input(self, t: Tensor) -> Tensor:
        if self._hcg is None or not isinstance(t, Tensor):
            return t
        if t.ndim <= self._seq_dim:
            return t  # no sequence dim (0-d scales, per-example lengths)
        mesh = self._hcg.mesh
        placements = []
        for name in mesh.dim_names:
            if name == self._hcg.sep_axis:
                placements.append(Shard(self._seq_dim))
            elif name == self._hcg.dp_axis and t.ndim > 0 and \
                    self._seq_dim != 0:
                # keep the batch dim data-parallel alongside sep
                placements.append(Shard(0))
            else:
                placements.append(Replicate())
        return shard_tensor(t, mesh, placements)

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)
