"""Shared helper: shard optimizer accumulators over a mesh axis.

Single implementation behind distributed.shard_optimizer,
sharding.group_sharded_parallel, and fleet's HybridParallelOptimizer
(DygraphShardingOptimizer analog, dygraph_sharding_optimizer.py:48).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec


def shard_optimizer_states(optimizer, mesh, axis: str):
    """Monkeypatch optimizer._add_accumulator so new accumulators land
    Shard(0) over `axis` when dim0 is divisible, else replicated.
    Idempotent: re-sharding with the same axis is a no-op."""
    if getattr(optimizer, "_sharded_states_axis", None) == axis:
        return optimizer
    degree = mesh.get_dim_size(axis)
    orig_add = optimizer._add_accumulator

    def sharded_add(name, param, fill_value=0.0, dtype=None):
        store = optimizer._accumulators.setdefault(name, {})
        if id(param) not in store:
            arr = orig_add(name, param, fill_value, dtype)
            spec = PartitionSpec(axis) if (
                arr.ndim > 0 and arr.shape[0] % degree == 0) else PartitionSpec()
            store[id(param)] = jax.device_put(
                arr, NamedSharding(mesh.jax_mesh, spec))
        return store[id(param)]

    optimizer._add_accumulator = sharded_add
    optimizer._sharded_states_axis = axis
    optimizer._sharded_states_mesh = mesh
    return optimizer
