"""Shared helper: shard optimizer accumulators over a mesh axis.

Single implementation behind distributed.shard_optimizer,
sharding.group_sharded_parallel, and fleet's HybridParallelOptimizer
(DygraphShardingOptimizer analog, dygraph_sharding_optimizer.py:48).

``offload=True`` places the accumulators in ``pinned_host`` memory (jax
memory kinds) — the ZeRO-offload analog of the reference's
group_sharded_stage3.py:85 cpu_offload: states live on host RAM between
steps and cross PCIe at the step boundary (H2D prefetch before the update,
D2H write-back after).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec


def _host_kind(device):
    kinds = {m.kind for m in device.addressable_memories()}
    if "pinned_host" in kinds:
        return "pinned_host"
    if "unpinned_host" in kinds:  # pragma: no cover - backend-dependent
        return "unpinned_host"
    raise NotImplementedError(
        f"offload=True: backend {device.platform} exposes no host memory "
        f"kind (have {sorted(kinds)})")


def shard_optimizer_states(optimizer, mesh, axis: str, offload: bool = False):
    """Patch optimizer._add_accumulator so new accumulators land Shard over
    `axis` on their first divisible dim (replicated when none divides),
    optionally in host memory. Idempotent; re-calling with different args
    re-points the ONE patch instead of chaining wrappers."""
    if getattr(optimizer, "_sharded_states_axis", None) == axis and \
            getattr(optimizer, "_sharded_states_offload", None) == offload:
        return optimizer
    degree = mesh.get_dim_size(axis)
    memory_kind = _host_kind(jax.devices()[0]) if offload else None
    if not hasattr(optimizer, "_orig_add_accumulator"):
        optimizer._orig_add_accumulator = optimizer._add_accumulator
    orig_add = optimizer._orig_add_accumulator

    def _sharded_put(v, kind=None):
        """device_put keeping the divisible-dim Shard spec (the one
        placement rule for both accumulator creation and the offload
        step-boundary transfers)."""
        from .sharding import _divisible_dim
        dim = _divisible_dim(v.shape, degree) if v.ndim else None
        parts = [None] * v.ndim
        if dim is not None:
            parts[dim] = axis
        spec = PartitionSpec(*parts)
        sharding = NamedSharding(mesh.jax_mesh, spec, memory_kind=kind) \
            if kind else NamedSharding(mesh.jax_mesh, spec)
        return jax.device_put(v, sharding)

    def sharded_add(name, param, fill_value=0.0, dtype=None):
        store = optimizer._accumulators.setdefault(name, {})
        if id(param) not in store:
            arr = orig_add(name, param, fill_value, dtype)
            store[id(param)] = _sharded_put(arr, memory_kind)
        return store[id(param)]

    optimizer._add_accumulator = sharded_add
    optimizer._sharded_states_axis = axis
    optimizer._sharded_states_offload = offload
    optimizer._sharded_states_mesh = mesh

    if memory_kind:
        # step-boundary transfers: H2D prefetch for the update, D2H
        # write-back to the sharded host residence
        optimizer._fetch_state_for_update = \
            lambda v: _sharded_put(v, "device")
        optimizer._restore_state_placement = \
            lambda v: _sharded_put(v, memory_kind)
    else:
        # drop any stale offload hooks from a prior offload=True wrap
        for attr in ("_fetch_state_for_update", "_restore_state_placement"):
            if attr in optimizer.__dict__:
                del optimizer.__dict__[attr]
    return optimizer
