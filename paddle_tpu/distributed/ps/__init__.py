"""Parameter-server training — documented out-of-scope stub.

Reference: paddle/fluid/distributed/ps (34.8k LoC: brpc BrpcPsServer/
Client, memory/SSD sparse tables, GEO/async/sync modes, heter PS) +
fleet.runtime.the_one_ps. SURVEY.md §2.1 N17 disposition: the PS stack
serves CPU-cluster sparse-recommendation workloads (billion-slot
embeddings on commodity hosts); the TPU north star is collective SPMD
training, where huge embeddings are sharded DistTensors over the mesh
(see fleet mp VocabParallelEmbedding and the MoE EP path).

Migration path for reference PS users:
- sparse embedding tables  -> nn.Embedding sharded Shard(0) over the mesh
  (vocab-parallel), optionally MoE/EP all-to-all for capacity
- async/GEO SGD            -> synchronous data parallel (the TPU ICI makes
  sync steps faster than the PS's async staleness trade)
- distributed serving      -> paddle_tpu.inference AOT executables

The entry points below exist so reference code paths fail loudly with
that guidance instead of AttributeError.
"""
from __future__ import annotations

_MSG = ("parameter-server mode is not part of the TPU build (SURVEY.md "
        "§2.1 N17): use collective SPMD training — sharded embeddings via "
        "shard_tensor/VocabParallelEmbedding replace PS sparse tables. ")


class PSCore:  # fluid.core PS handle analog
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


def init_server(*args, **kwargs):
    raise NotImplementedError(_MSG)


def init_worker(*args, **kwargs):
    raise NotImplementedError(_MSG)


def run_server(*args, **kwargs):
    raise NotImplementedError(_MSG)


def stop_worker(*args, **kwargs):
    raise NotImplementedError(_MSG)


__all__ = ["init_server", "init_worker", "run_server", "stop_worker",
           "PSCore"]
