"""Runtime SPMD mesh layer: the bridge from the static shard plan to
real ``jax.sharding`` programs.

``analysis/sharding.py`` + ``tools/shard_check.py`` can statically cost
every PLAN_7B variant, but until this module the runtime was
single-chip. ``MeshRuntime`` materializes a ``jax.sharding.Mesh`` with
the named ``(data, fsdp, tensor)`` axes from either an explicit axis
dict or the launcher env (single-process multi-device AND multi-process
gloo worlds both work), and translates the plan's shard-policy mirror
(``analysis.sharding.plan_shard_dim`` / ``divisible_dim`` — the single
source of truth the static checks use) into real ``NamedSharding``s:

* **training** (``train_plan``): parameters/masters/optimizer state
  shard their plan dim over ``fsdp`` (ZeRO stage-3 storage sharding,
  with a second divisible dim over ``tensor``); activations/batch shard
  over the ``data`` axis. The fused donating TrainStep consumes the
  plan via ``jit``'s ``in_shardings``/``out_shardings``
  (``hapi.Model.prepare(jit=True, plan=...)``).
* **serving** (``shard_serving``): a batcher becomes a tensor-parallel
  shard group — weights ``P(None, 'tensor')`` (column-parallel: every
  collective is a gather, no cross-shard reduction, so greedy decoding
  stays token-exact), KV caches/pages sharded on the heads dim. Member
  death surfaces as a non-retryable ``TPMemberDied`` that rides the
  gateway's existing retry-then-declare-dead + token-exact requeue
  machinery.

Every mesh program is **gated at runtime by the same SH/MEM analyzer**
the static plane uses: a spec whose shard dim does not divide refuses
with SH201, a step whose predicted per-chip live bytes exceed the HBM
budget refuses with MEM301 (``MeshProgramRejected`` carries the
findings), and ``measured_live_bytes`` reads the compiled executable's
buffer assignment so the runtime and ``analysis/memory.py`` verify each
other. ``describe()`` dumps the exact specs for
``tools/shard_check.py --from-runtime``.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "MeshRuntime", "TrainMeshPlan", "ShardGroup", "MeshProgramRejected",
    "TPMemberDied", "current_axis_label", "axis_scope",
    "spec_to_json", "spec_from_json", "spec_of_array",
]

#: canonical axis order; size-1 axes are kept in the mesh so specs can
#: always name them (a size-1 axis shards nothing and costs nothing)
AXIS_ORDER = ("data", "fsdp", "tensor")

GIB = 1024 ** 3


class MeshProgramRejected(RuntimeError):
    """A mesh program the SH/MEM analyzer refuses to run.

    ``findings`` holds the ``analysis.findings.Finding`` objects; the
    message leads with the rule codes (SH201, MEM301, ...) so callers
    and logs see the same identifiers the static gate prints.
    """

    def __init__(self, findings):
        self.findings = list(findings)
        codes = ",".join(sorted({f.rule for f in self.findings}))
        detail = "; ".join(str(f) for f in self.findings[:4])
        super().__init__(f"[{codes}] mesh program refused: {detail}")


class TPMemberDied(RuntimeError):
    """A member of a tensor-parallel shard group is gone. Deliberately
    NOT retryable: the member held 1/N of the weights and KV — the whole
    group must be declared dead and its requests requeued token-exact
    onto survivors (the gateway's existing failure machinery)."""


# -- per-axis collective telemetry context ----------------------------------
# Eager collectives run through distributed.collective._watched, whose
# counters are labeled by op only. When a mesh axis scope is active the
# wrapper ALSO feeds axis-labeled twins; with no scope armed (every
# single-process run today) nothing new is emitted, keeping existing
# output byte-identical.

_AXIS_LABEL = threading.local()


def current_axis_label() -> Optional[str]:
    return getattr(_AXIS_LABEL, "axis", None)


@contextlib.contextmanager
def axis_scope(axis: str):
    """Label collectives issued inside the scope with a mesh axis name."""
    prev = current_axis_label()
    _AXIS_LABEL.axis = axis
    try:
        yield
    finally:
        _AXIS_LABEL.axis = prev


# -- ShardSpec serialization --------------------------------------------------
# The elastic checkpoint manifest records each param's placement as JSON;
# these two are the ONE round-trip (tuple axes <-> lists, None <-> null)
# so a checkpoint saved under any mesh can name its layout portably.

def spec_to_json(spec_dims: Sequence) -> list:
    """Per-dim PartitionSpec entries -> JSON-able list."""
    return [list(d) if isinstance(d, tuple) else d for d in spec_dims]


def spec_from_json(obj: Sequence) -> Tuple:
    """Inverse of ``spec_to_json``."""
    return tuple(tuple(d) if isinstance(d, list) else d for d in obj)


def spec_of_array(arr, ndim: Optional[int] = None) -> Tuple:
    """The per-dim spec a live ``jax.Array``'s NamedSharding encodes,
    padded with None to the array's rank (PartitionSpec may be shorter).
    Arrays without a NamedSharding (single-device, host) are replicated."""
    n = int(ndim if ndim is not None else getattr(arr, "ndim", 0))
    sharding = getattr(arr, "sharding", None)
    spec_obj = getattr(sharding, "spec", None)
    dims: List = list(spec_obj) if spec_obj is not None else []
    dims = dims[:n] + [None] * (n - len(dims))
    return tuple(dims)


def _analysis_sharding():
    from ..analysis import sharding as _s
    return _s


def _analysis_memory():
    from ..analysis import memory as _m
    return _m


def _mesh_gauges():
    from ..observability.metrics import get_registry
    reg = get_registry()
    return (reg.gauge("mesh.live_bytes_measured",
                      "per-chip live bytes of the latest compiled mesh "
                      "program (XLA buffer assignment)"),
            reg.gauge("mesh.live_bytes_predicted",
                      "per-chip live bytes analysis/memory.py predicts "
                      "for the same program"),
            reg.gauge("mesh.live_bytes_agreement",
                      "measured / predicted per-chip live bytes"))


class MeshRuntime:
    """Named-axis device mesh + the plan -> NamedSharding policies."""

    def __init__(self, axes: Optional[Dict[str, int]] = None,
                 devices: Optional[Sequence] = None):
        devs = list(devices if devices is not None else jax.devices())
        if axes is None:
            axes = {"data": len(devs), "fsdp": 1, "tensor": 1}
        norm: Dict[str, int] = {}
        for name in AXIS_ORDER:
            norm[name] = int(axes.get(name, 1))
        extra = set(axes) - set(AXIS_ORDER)
        if extra:
            raise ValueError(f"unknown mesh axes {sorted(extra)}; "
                             f"this runtime names {AXIS_ORDER}")
        size = int(np.prod(list(norm.values())))
        if size < 1 or size > len(devs):
            raise ValueError(
                f"mesh {norm} needs {size} device(s) but only "
                f"{len(devs)} are visible")
        self.axes = norm
        shape = tuple(norm[a] for a in AXIS_ORDER)
        grid = np.array(devs[:size], dtype=object).reshape(shape)
        self.mesh = Mesh(grid, AXIS_ORDER)
        self.size = size

    # -- construction ---------------------------------------------------------
    @classmethod
    def from_env(cls, default_tensor: int = 1) -> "MeshRuntime":
        """Build the mesh from the launcher env.

        ``PADDLE_MESH_SHAPE`` ("data:1,fsdp:2,tensor:2") wins. Otherwise
        a multi-process world (``PADDLE_TRAINERS_NUM`` > 1) initializes
        the distributed runtime first (gloo on the CPU proxy) and spans
        every global device; single-process spans the local devices.
        The default split puts everything on ``data`` except an optional
        trailing ``tensor`` degree.
        """
        spec = os.environ.get("PADDLE_MESH_SHAPE")
        if int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
            from .collective import init_parallel_env
            init_parallel_env()   # PJRT distributed runtime + gloo + store
        if spec:
            axes: Dict[str, int] = {}
            for part in spec.split(","):
                name, _, deg = part.partition(":")
                axes[name.strip()] = int(deg or 1)
            return cls(axes)
        n = len(jax.devices())
        t = default_tensor if n % max(default_tensor, 1) == 0 else 1
        return cls({"data": n // max(t, 1), "fsdp": 1, "tensor": t})

    @property
    def multiprocess(self) -> bool:
        return jax.process_count() > 1

    def axis_size(self, name: str) -> int:
        return self.axes.get(name, 1)

    def spec(self):
        """The static mirror (``analysis.sharding.MeshSpec``)."""
        return _analysis_sharding().MeshSpec(self.axes)

    def process_mesh(self):
        """ProcessMesh wrapper (fleet/auto_parallel interop)."""
        from .auto_parallel import ProcessMesh
        return ProcessMesh(None, _jax_mesh=self.mesh)

    def named_sharding(self, spec_dims: Sequence) -> NamedSharding:
        """``spec_dims``: per-tensor-dim axis name / tuple / None."""
        return NamedSharding(self.mesh, PartitionSpec(*spec_dims))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    # -- the plan policy mirror ----------------------------------------------
    def train_param_spec(self, shape: Sequence[int],
                         name: str = "") -> Tuple:
        """PLAN_7B placement for one parameter: the plan's declared dim
        (``plan_shard_dim``: norms replicate, 2D dim0, 3D dim1) shards
        over ``fsdp``; the next divisible dim shards over ``tensor``.
        Falls back through ``divisible_dim`` exactly like the static
        SH204 check, replicating when nothing divides."""
        s = _analysis_sharding()
        ndim = len(shape)
        spec: List[Optional[str]] = [None] * ndim
        f, t = self.axis_size("fsdp"), self.axis_size("tensor")
        if ndim < 2 or (name and name.startswith("ln")):
            return tuple(spec)
        primary = s.plan_shard_dim(name or "w", shape)
        if f > 1:
            if primary is None or shape[primary] % f:
                primary = s.divisible_dim(shape, f)
            if primary is not None:
                spec[primary] = "fsdp"
        if t > 1:
            for d in range(ndim - 1, -1, -1):   # prefer the trailing dim
                if spec[d] is None and shape[d] % t == 0 and shape[d] >= t:
                    spec[d] = "tensor"
                    break
        return tuple(spec)

    def batch_spec(self, shape: Sequence[int],
                   data_axes: Sequence[str] = ("data",)) -> Tuple:
        """Batch placement: dim0 over the data-parallel axes when it
        divides, else replicated (an indivisible batch is a gate error
        only when the caller declared it sharded)."""
        spec: List[Optional[object]] = [None] * len(shape)
        axes = tuple(a for a in data_axes if self.axis_size(a) > 1)
        if not shape or not axes:
            return tuple(spec)
        deg = int(np.prod([self.axis_size(a) for a in axes]))
        if deg > 1 and shape[0] % deg == 0:
            spec[0] = axes[0] if len(axes) == 1 else tuple(axes)
        return tuple(spec)

    def serving_weight_spec(self, shape: Sequence[int],
                            name: str = "") -> Tuple:
        """Serving TP placement: ``P(None, 'tensor')`` for matrices
        (column-parallel — gathers only, never a cross-shard reduction,
        so greedy decode stays token-exact), replicate vectors/norms."""
        t = self.axis_size("tensor")
        ndim = len(shape)
        spec: List[Optional[str]] = [None] * ndim
        if t <= 1 or ndim < 2:
            return tuple(spec)
        d = ndim - 1                       # trailing (output/feature) dim
        if shape[d] % t == 0 and shape[d] >= t:
            spec[d] = "tensor"
        return tuple(spec)

    def serving_cache_spec(self, ndim: int, heads_dim: int) -> Tuple:
        """KV caches/pages shard the heads dim over ``tensor``."""
        spec: List[Optional[str]] = [None] * ndim
        if self.axis_size("tensor") > 1:
            spec[heads_dim] = "tensor"
        return tuple(spec)

    # -- placement ------------------------------------------------------------
    def place(self, value, spec_dims: Sequence):
        """Commit a host/device array to the mesh under ``spec_dims``.

        Single-process: plain ``device_put``. Multi-process: every rank
        holds the full host value (deterministic init), so the global
        array is assembled shard-by-shard via ``make_array_from_callback``
        — the only portable way to build an array spanning
        non-addressable devices.
        """
        sharding = self.named_sharding(spec_dims)
        if (isinstance(value, jax.Array)
                and getattr(value, "sharding", None) is not None
                and set(value.sharding.device_set)
                == set(self.mesh.devices.flat)):
            # already mesh-resident (e.g. a previous step's output): jit
            # reshards if the spec differs; np.asarray would fail on a
            # multi-host array anyway
            return value
        if not self.multiprocess:
            return jax.device_put(value, sharding)
        host = np.asarray(value)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    def place_from_shards(self, global_shape: Sequence[int], dtype,
                          spec_dims: Sequence, chunks: Sequence[dict],
                          read_chunk) -> "jax.Array":
        """Re-place a checkpointed tensor under THIS mesh from whatever
        shard layout it was SAVED under.

        ``chunks`` describe the stored pieces (each a dict with
        ``offset`` and ``shape``); ``read_chunk(i)`` returns chunk i as
        a host array already in the target dtype. The assembly runs the
        same overlap math the reshard-on-load path uses
        (``distributed.checkpoint.save_load.overlap_slices``), but
        per-TARGET-shard inside ``jax.make_array_from_callback`` — only
        the regions this process's devices need are ever materialized,
        so a 2x2-mesh checkpoint restores onto 1x4, 4x1, or a single
        device without the full tensor touching host memory twice."""
        from .checkpoint.save_load import overlap_slices
        gshape = tuple(int(d) for d in global_shape)
        sharding = self.named_sharding(spec_dims)
        np_target = np.dtype(dtype) if not hasattr(dtype, "itemsize") \
            else dtype

        def cb(index):
            dst_off, dst_shape = [], []
            for sl, dim in zip(index, gshape):
                start = 0 if sl.start is None else int(sl.start)
                stop = dim if sl.stop is None else int(sl.stop)
                dst_off.append(start)
                dst_shape.append(stop - start)
            dst_off, dst_shape = tuple(dst_off), tuple(dst_shape)
            buf = np.empty(dst_shape, dtype=np_target)
            filled = np.zeros(dst_shape, dtype=bool)
            for i, ch in enumerate(chunks):
                ov = overlap_slices(dst_off, dst_shape,
                                    tuple(ch["offset"]),
                                    tuple(ch["shape"]))
                if ov is None:
                    continue
                dst_sl, src_sl = ov
                buf[dst_sl] = read_chunk(i)[src_sl]
                filled[dst_sl] = True
            if not filled.all():
                raise ValueError(
                    f"stored chunks do not cover the target shard at "
                    f"offset {dst_off} (missing {int((~filled).sum())} "
                    "elems) — torn or incomplete checkpoint")
            return buf

        return jax.make_array_from_callback(gshape, sharding, cb)

    # -- the runtime SH/MEM gate ----------------------------------------------
    def gate_specs(self, entries: Sequence[Tuple[str, Sequence[int],
                                                 Sequence]],
                   file: str = "<runtime>") -> None:
        """SH201 for every (name, shape, spec) about to be placed; raise
        ``MeshProgramRejected`` on any error finding — same rule, same
        code, same message shape as the static gate."""
        s = _analysis_sharding()
        mesh_spec = self.spec()
        findings = []
        for name, shape, spec in entries:
            findings.extend(s.check_spec_divisibility(
                name, tuple(shape), tuple(spec), mesh_spec, file=file))
        if findings:
            raise MeshProgramRejected(findings)

    def gate_memory(self, predicted_bytes: float,
                    budget_gib: Optional[float],
                    file: str = "<runtime>") -> None:
        """MEM301 when the predicted per-chip live bytes exceed the HBM
        budget — refused BEFORE compiling, like the static plan gate."""
        if budget_gib is None or predicted_bytes <= budget_gib * GIB:
            return
        from ..analysis.findings import ERROR, Finding
        raise MeshProgramRejected([Finding(
            "MEM301",
            f"mesh program needs {predicted_bytes / GIB:.3f} GiB/chip "
            f"but the budget is {budget_gib:.3f} GiB — OOM before "
            "step 1",
            file=file, severity=ERROR,
            extra={"peak_bytes": predicted_bytes,
                   "budget_gib": budget_gib})])

    # -- the training plan ----------------------------------------------------
    def train_plan(self, *, budget_gib: Optional[float] = None,
                   data_axes: Sequence[str] = ("data",),
                   zero3_gather: bool = True,
                   param_names: Optional[Dict[int, str]] = None
                   ) -> "TrainMeshPlan":
        return TrainMeshPlan(self, budget_gib=budget_gib,
                             data_axes=tuple(data_axes),
                             zero3_gather=zero3_gather,
                             param_names=param_names or {})

    # -- serving: tensor-parallel shard group ---------------------------------
    def shard_serving(self, batcher, group_name: str = "tp"
                      ) -> "ShardGroup":
        """Turn a batcher into a tensor-parallel shard group: weights
        ``P(None,'tensor')``, dense KV caches (and paged pools) sharded
        on the heads dim. Gated by SH201 (head divisibility) first.
        Returns the ``ShardGroup`` (also attached as
        ``batcher.shard_group`` — the batcher's step heartbeats it)."""
        cfg = batcher.model.config
        t = self.axis_size("tensor")
        entries = [("num_attention_heads", (cfg.num_attention_heads,),
                    ("tensor",)),
                   ("num_key_value_heads",
                    (getattr(cfg, "num_key_value_heads", None)
                     or cfg.num_attention_heads,), ("tensor",))]
        self.gate_specs(entries, file="<serving>")

        placed = {}
        for pname, p in batcher.model.named_parameters():
            spec = self.serving_weight_spec(tuple(p.shape), name=pname)
            if any(a is not None for a in spec):
                p._data = self.place(p._data, spec)
                placed[pname] = {"shape": list(p.shape),
                                 "dtype": str(p._data.dtype),
                                 "spec": list(spec)}
        # dense KV cache [L, 2, B, kvh, s_max, d]: heads dim 3
        caches = getattr(batcher, "_caches", None)
        if caches is not None and getattr(caches, "ndim", 0) == 6:
            batcher._caches._data = self.place(
                caches._data, self.serving_cache_spec(6, 3))
        # paged pool per layer [n_pages+1, H, bs, D]: heads dim 1
        pool = getattr(batcher, "_pool", None)
        if pool is not None:
            for i, page in enumerate(getattr(pool, "k", []) or []):
                pool.k[i] = self.place(page, self.serving_cache_spec(4, 1))
            for i, page in enumerate(getattr(pool, "v", []) or []):
                pool.v[i] = self.place(page, self.serving_cache_spec(4, 1))
        group = ShardGroup(group_name, self, axis="tensor",
                           placed_params=placed)
        batcher.shard_group = group
        return group

    # -- memory cross-check ---------------------------------------------------
    @staticmethod
    def measured_live_bytes(compiled) -> Optional[dict]:
        """Per-chip byte accounting of a compiled executable, from XLA's
        own buffer assignment. ``peak_bytes`` is
        ``args + temp + max(0, out - aliased)`` — the exact formula the
        recorded ``PLAN_7B.json`` footprints use; ``argument_bytes`` is
        the resident state (what stays live between steps). None when
        the backend exposes no memory analysis."""
        try:
            ma = compiled.memory_analysis()
            args = int(ma.argument_size_in_bytes)
            out = int(ma.output_size_in_bytes)
            alias = int(ma.alias_size_in_bytes)
            temp = int(ma.temp_size_in_bytes)
        except Exception:
            return None
        return {"argument_bytes": args, "output_bytes": out,
                "alias_bytes": alias, "temp_bytes": temp,
                "peak_bytes": args + temp + max(0, out - alias)}

    def verify_live_bytes(self, measured: dict, predicted: dict,
                          tolerance: float = 0.10,
                          peak_slack: float = 1.05) -> dict:
        """The runtime/static memory cross-check, two-sided:

        * **state** — XLA's resident argument bytes must agree with the
          spec-derived prediction within ``tolerance``. This is the
          bytes-per-chip claim the plan's sharding math makes (the term
          that dominates every PLAN_7B footprint), and both sides count
          the same buffers, so agreement is tight.
        * **peak** — the liveness walk does not model XLA fusion, so its
          peak is a deliberate upper bound; the check is SOUNDNESS
          (``measured <= predicted * peak_slack``), i.e. the static
          MEM301 gate never under-predicts what the chip will hold.

        Publishes the ``mesh.live_bytes_*`` gauges; the caller decides
        whether a miss is fatal."""
        m_state = float(measured["argument_bytes"])
        p_state = float(predicted["predicted_state_bytes"]) or 1.0
        m_peak = float(measured["peak_bytes"])
        p_peak = float(predicted["predicted_peak_bytes"]) or 1.0
        ratio = m_state / p_state
        m_g, p_g, a_g = _mesh_gauges()
        m_g.set(m_state)
        p_g.set(p_state)
        a_g.set(ratio)
        return {"measured_state_bytes": int(m_state),
                "predicted_state_bytes": p_state,
                "state_ratio": ratio,
                "within_tolerance": abs(ratio - 1.0) <= tolerance,
                "measured_peak_bytes": int(m_peak),
                "predicted_peak_bytes": p_peak,
                "peak_ratio": m_peak / p_peak,
                "peak_bound_sound": m_peak <= p_peak * peak_slack}

    # -- interop with the ZeRO runtime (distributed/sharding.py) --------------
    @staticmethod
    def sharding_axis(group=None):
        """The (mesh, axis) the group-sharded (ZeRO) runtime shards
        over: the hybrid topology's 'sharding' axis when fleet armed
        one, else the given/world group's own axis. Single home for the
        derivation ``distributed/sharding.py`` used to duplicate."""
        from .fleet.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
            return hcg.mesh, "sharding"
        from .collective import init_parallel_env
        g = group or init_parallel_env()
        return g.mesh, g.axis_name

    # -- the runtime -> static handoff ----------------------------------------
    def describe(self, train_plan: Optional["TrainMeshPlan"] = None,
                 serving: Optional["ShardGroup"] = None,
                 budget_gib: Optional[float] = None) -> dict:
        """JSON-able dump of the EXACT specs this runtime will execute,
        for ``tools/shard_check.py --from-runtime`` (closes the
        static/runtime drift hole: CI lints what runs, not a mirror)."""
        out = {
            "kind": "mesh_runtime",
            "mesh": dict(self.axes),
            "n_devices": self.size,
            "multiprocess": self.multiprocess,
            "hbm_per_chip_gib": budget_gib,
            "params": {},
        }
        if train_plan is not None:
            out["params"].update(train_plan.describe_params())
            out["batch"] = train_plan.describe_batch()
            if budget_gib is None:
                out["hbm_per_chip_gib"] = train_plan.budget_gib
            report = getattr(train_plan, "memory_report", None)
            if report:
                out["memory"] = {k: float(v) for k, v in report.items()
                                 if isinstance(v, (int, float))}
        if serving is not None:
            out["serving"] = serving.describe()
        return out


class TrainMeshPlan:
    """The shardings one fused TrainStep compiles with.

    Built by ``MeshRuntime.train_plan``; consumed by
    ``jit.TrainStep(mesh_plan=...)``:

    * ``register_params`` fixes the param order and derives every spec;
    * ``gate()`` runs the SH201 divisibility check over the derived
      specs plus the MEM301 budget check against the liveness-walk
      prediction (``analysis.memory.peak_hbm_estimate`` with the specs'
      shard divisors) — refusal raises ``MeshProgramRejected``;
    * ``step_shardings`` yields the ``in_shardings``/``out_shardings``
      pytrees matching the pure step's signature;
    * ``place_state``/``place_batch`` commit live buffers;
    * ``collective_bytes_by_axis`` is the analytic per-axis comm volume
      of one step (feeds the roofline attribution split).

    ``zero3_gather=True`` keeps compute numerically identical to a
    single device: parameters live sharded (storage) and are constrained
    replicated at use, so XLA all-gathers them and frees the copies —
    the documented stage-3 semantics — and no cross-shard reduction
    reorders any sum.
    """

    def __init__(self, runtime: MeshRuntime, budget_gib=None,
                 data_axes=("data",), zero3_gather=True, param_names=None):
        self.runtime = runtime
        self.budget_gib = budget_gib
        self.data_axes = tuple(data_axes)
        self.zero3_gather = zero3_gather
        self._names: Dict[int, str] = dict(param_names or {})
        self._param_specs: List[Tuple] = []
        self._param_shapes: List[Tuple[int, ...]] = []
        self._param_dtypes: List[str] = []
        self._batch_specs: List[Tuple] = []
        self._batch_shapes: List[Tuple[int, ...]] = []
        self.gated = False
        self.memory_report: Optional[dict] = None

    # -- registration ---------------------------------------------------------
    def _name_of(self, i: int, p) -> str:
        return self._names.get(i) or getattr(p, "name", None) or f"p{i}"

    def register_params(self, params) -> None:
        self._param_specs = []
        self._param_shapes = []
        self._param_dtypes = []
        for i, p in enumerate(params):
            shape = tuple(int(d) for d in p.shape)
            self._param_shapes.append(shape)
            self._param_dtypes.append(str(getattr(p, "dtype", "float32")))
            self._param_specs.append(self.runtime.train_param_spec(
                shape, name=self._name_of(i, p)))

    def register_batch(self, batch_arrays) -> None:
        self._batch_shapes = [tuple(int(d) for d in getattr(b, "shape", ()))
                              for b in batch_arrays]
        self._batch_specs = [self.runtime.batch_spec(s, self.data_axes)
                             for s in self._batch_shapes]

    # -- gate -----------------------------------------------------------------
    def gate(self, jaxpr=None, donate: Sequence[int] = (),
             invar_specs=None) -> None:
        entries = [(f"param:{i}", s, spec) for i, (s, spec) in
                   enumerate(zip(self._param_shapes, self._param_specs))]
        entries += [(f"batch:{i}", s, spec) for i, (s, spec) in
                    enumerate(zip(self._batch_shapes, self._batch_specs))]
        self.runtime.gate_specs(entries, file="<train_plan>")
        if jaxpr is not None:
            predicted = self.predict_live_bytes(jaxpr, donate=donate,
                                                invar_specs=invar_specs)
            self.memory_report = dict(predicted,
                                      budget_gib=self.budget_gib)
            self.runtime.gate_memory(predicted["predicted_peak_bytes"],
                                     self.budget_gib,
                                     file="<train_plan>")
        self.gated = True

    def predict_live_bytes(self, jaxpr, donate: Sequence[int] = (),
                           invar_specs=None) -> dict:
        """analysis/memory.py's liveness walk, per-chip: each invar's
        bytes divide by its shard degree; intermediates divide by the
        data-parallel degree (activations shard on batch).
        ``predicted_state_bytes`` (the resident inputs) is exact by
        construction; ``predicted_peak_bytes`` is a fusion-blind upper
        bound — see ``MeshRuntime.verify_live_bytes``."""
        mem = _analysis_memory()
        spec = self.runtime.spec()
        shards = None
        if invar_specs is not None:
            shards = [max(1, int(round(1.0 / _shard_fraction(
                spec, s)))) for s in invar_specs]
        dp = int(np.prod([self.runtime.axis_size(a)
                          for a in self.data_axes])) or 1
        est = mem.peak_hbm_estimate(jaxpr, donate=donate,
                                    invar_shards=shards,
                                    default_shards=dp)
        return {"predicted_peak_bytes": float(est["peak_bytes"]),
                "predicted_state_bytes": float(est["input_bytes"])}

    # -- sharding pytrees -----------------------------------------------------
    def param_sharding(self, i: int) -> NamedSharding:
        return self.runtime.named_sharding(self._param_specs[i])

    def state_sharding(self, i: int, leaf_shape) -> NamedSharding:
        """Optimizer-state leaf: param-shaped accumulators inherit the
        param's placement; anything else (scalars) replicates."""
        if tuple(leaf_shape) == self._param_shapes[i]:
            return self.param_sharding(i)
        return self.runtime.replicated

    def batch_sharding(self, j: int) -> NamedSharding:
        return self.runtime.named_sharding(self._batch_specs[j])

    def step_shardings(self, p_arrays, masters, opt_states, extra_arrays,
                       other_grads_in, batch, n_extra_out=None):
        """(in_shardings, out_shardings) matching the pure step
        ``(p, masters, opt_states, extra, other_grads, rng, lr, *batch)
        -> (loss, new_p, new_masters, new_states, new_extra,
            new_other_grads, new_key)``. ``n_extra_out`` is the mutated
        subset of ``extra`` the step returns (defaults to all)."""
        rep = self.runtime.replicated
        p_sh = [self.param_sharding(i) for i in range(len(p_arrays))]
        m_sh = [None if m is None else self.param_sharding(i)
                for i, m in enumerate(masters)]
        st_sh = [{k: self.state_sharding(i, getattr(v, "shape", ()))
                  for k, v in st.items()}
                 for i, st in enumerate(opt_states)]
        ex_sh = [rep for _ in extra_arrays]
        og_sh = [None if g is None else rep for g in other_grads_in]
        self.register_batch(batch)
        b_sh = [self.batch_sharding(j) for j in range(len(batch))]
        in_sh = (p_sh, m_sh, st_sh, ex_sh, og_sh, rep, rep, *b_sh)
        n_out = len(extra_arrays) if n_extra_out is None else n_extra_out
        out_sh = (rep, p_sh, m_sh, st_sh, [rep] * n_out,
                  [rep] * len(other_grads_in), rep)
        return in_sh, out_sh

    @staticmethod
    def flat_invar_specs(in_shardings) -> List[Tuple]:
        """Flatten an ``in_shardings`` pytree to per-invar spec tuples,
        aligned with the traced jaxpr's invars (None entries are empty
        pytree nodes on both sides, so they drop out identically)."""
        import jax.tree_util as jtu
        return [tuple(s.spec) for s in jtu.tree_leaves(in_shardings)]

    # -- in-step constraints --------------------------------------------------
    def constrain_param_for_use(self, i: int, arr):
        """Inside the step: gather the stored shard for compute (stage-3
        semantics) when ``zero3_gather``; otherwise leave placement to
        GSPMD propagation."""
        if not self.zero3_gather:
            return arr
        return jax.lax.with_sharding_constraint(
            arr, self.runtime.replicated)

    def constrain_grad(self, i: int, grad):
        """Backward: land the grad on the param's placement so the
        update runs on shards. In gather-at-use (exact) mode the grad is
        first pinned replicated: without the pin GSPMD propagates the
        shard constraint INTO the producing op (e.g. the embedding-grad
        scatter-add), repartitioning its accumulation order — a 1-ulp
        drift that breaks bitwise equality with the single-device step.
        Pinned, the full grad completes identically and the reshard is
        an exact slice."""
        if self.zero3_gather:
            grad = jax.lax.with_sharding_constraint(
                grad, self.runtime.replicated)
        return jax.lax.with_sharding_constraint(
            grad, self.param_sharding(i))

    # -- placement ------------------------------------------------------------
    def place_state(self, params, masters, opt_states):
        """Commit params (+ masters + optimizer accumulators) to their
        sharded residence. Runs AFTER the eager discovery step (eager
        ops cannot touch non-addressable shards in a multi-process
        world)."""
        for i, p in enumerate(params):
            p._data = self.runtime.place(p._data, self._param_specs[i])
        placed_masters = []
        for i, m in enumerate(masters):
            placed_masters.append(
                None if m is None
                else self.runtime.place(m, self._param_specs[i]))
        placed_states = []
        for i, st in enumerate(opt_states):
            placed_states.append({
                k: self.runtime.place(
                    v, self._param_specs[i]
                    if tuple(getattr(v, "shape", ())) ==
                    self._param_shapes[i] else
                    (None,) * len(getattr(v, "shape", ())))
                for k, v in st.items()})
        return placed_masters, placed_states

    def place_batch(self, batch_arrays):
        self.register_batch(batch_arrays)
        return [self.runtime.place(b, self._batch_specs[j])
                for j, b in enumerate(batch_arrays)]

    # -- per-axis collective accounting ---------------------------------------
    def collective_bytes_by_axis(self) -> Dict[str, float]:
        """Analytic per-chip collective bytes of ONE step, by axis:
        stage-3 all-gathers each sharded param twice (forward + backward
        re-gather) and reduce-scatters its grad — ``(N-1)/N`` of the
        bytes move, attributed to every axis the spec names (the same
        model as ``analysis.sharding.plan_step_collective_bytes``,
        resolved per-param so mixed placements price correctly)."""
        s = _analysis_sharding()
        out: Dict[str, float] = {}
        for shape, dtype, spec in zip(self._param_shapes,
                                      self._param_dtypes,
                                      self._param_specs):
            axes = {a for d in spec if d is not None
                    for a in (d if isinstance(d, tuple) else (d,))}
            if not axes:
                continue
            nb = s.nbytes(shape, dtype)
            for a in axes:
                n = self.runtime.axis_size(a)
                if n <= 1:
                    continue
                frac = (n - 1) / n
                gathers = 2.0 if self.zero3_gather else 1.0
                out[a] = out.get(a, 0.0) + (gathers + 1.0) * nb * frac
        dp_axes = [a for a in self.data_axes if self.runtime.axis_size(a) > 1]
        if dp_axes and any(any(d is not None for d in sp)
                           for sp in self._batch_specs):
            # data-parallel grad psum: every param's full grad bytes
            grad_nb = sum(s.nbytes(sh, dt) for sh, dt in
                          zip(self._param_shapes, self._param_dtypes))
            for a in dp_axes:
                n = self.runtime.axis_size(a)
                out[a] = out.get(a, 0.0) + 2.0 * grad_nb * (n - 1) / n
        return out

    # -- describe -------------------------------------------------------------
    def describe_params(self) -> dict:
        return {f"param:{i}" if not self._names.get(i) else self._names[i]:
                {"shape": list(s), "dtype": d, "spec": list(spec)}
                for i, (s, d, spec) in enumerate(
                    zip(self._param_shapes, self._param_dtypes,
                        self._param_specs))}

    def describe_batch(self) -> list:
        return [{"shape": list(s), "spec": list(spec)}
                for s, spec in zip(self._batch_shapes, self._batch_specs)]


def _shard_fraction(mesh_spec, spec_dims) -> float:
    deg = 1
    for d in spec_dims:
        for a in (d if isinstance(d, tuple) else (d,) if d else ()):
            deg *= mesh_spec.axes.get(a, 1)
    return 1.0 / max(deg, 1)


class ShardGroup:
    """A tensor-parallel serving shard group: one logical replica whose
    weights/KV live split over the mesh's ``tensor`` axis. One member
    per device on that axis; a dead member means the whole group cannot
    step (its shard is gone) — ``heartbeat()`` raises the non-retryable
    ``TPMemberDied`` the pool turns into declare-dead + token-exact
    requeue. The ``serving.tp_member`` chaos point injects member
    failures for drills."""

    def __init__(self, name: str, runtime: MeshRuntime,
                 axis: str = "tensor", placed_params=None):
        self.name = name
        self.runtime = runtime
        self.axis = axis
        self.members = [f"{name}/{axis}{i}"
                        for i in range(runtime.axis_size(axis))]
        self.placed_params = dict(placed_params or {})
        self._dead: List[str] = []

    @property
    def degree(self) -> int:
        return len(self.members)

    @property
    def failed_members(self) -> List[str]:
        return list(self._dead)

    def fail_member(self, member: str, reason: str = "") -> None:
        if member not in self.members:
            raise ValueError(f"{member!r} is not in {self.members}")
        if member not in self._dead:
            self._dead.append(member)
            from ..observability.metrics import get_registry
            get_registry().counter(
                "mesh.tp_member_deaths",
                "tensor-parallel shard-group members declared dead",
                labelnames=("group",)).labels(group=self.name).inc()

    def heartbeat(self) -> None:
        """Called by the batcher at every step. Chaos faults at
        ``serving.tp_member`` mark the last member dead; any dead member
        makes the group unsteppable."""
        from ..resilience.chaos import fault_point
        try:
            fault_point("serving.tp_member")
        except Exception as exc:
            self.fail_member(self.members[-1], reason=str(exc))
        if self._dead:
            raise TPMemberDied(
                f"shard group {self.name!r}: member(s) "
                f"{self._dead} dead — {self.degree}-way tensor-parallel "
                "weights/KV are incomplete; declare the group dead and "
                "requeue its requests")

    def describe(self) -> dict:
        return {"group": self.name, "axis": self.axis,
                "members": list(self.members),
                "failed": list(self._dead),
                "params": {k: (dict(v) if isinstance(v, dict) else list(v))
                           for k, v in self.placed_params.items()}}
