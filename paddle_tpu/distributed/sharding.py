"""Group-sharded (ZeRO) parallelism.

Reference: paddle.distributed.sharding.group_sharded_parallel
(distributed/sharding/group_sharded.py) dispatching to GroupShardedStage2
(grad+optimizer sharding, group_sharded_stage2.py:46) and GroupShardedStage3
(parameter sharding with prefetch + CPU offload, group_sharded_stage3.py:85);
stage 1 via DygraphShardingOptimizer (optimizer-state sharding).

TPU-native: ZeRO stages are PLACEMENT POLICIES over a 'sharding' mesh axis —
  stage 1 (os):    optimizer states Shard over the axis
  stage 2 (os_g):  + gradients annotated Shard (reduce-scatter backward)
  stage 3 (p_g_os):+ parameters Shard; XLA all-gathers params where used
                    and frees the gathered copies.

Parameter sharding picks the FIRST dim divisible by the axis degree (dim0
preferred, matching the reference's flat-storage split; a dim0-odd matrix
still shards on its other dim instead of silently replicating). Params with
no divisible dim replicate with an explicit warning.

``offload=True`` is REAL: optimizer states (and master weights) land in
``pinned_host`` memory via jax memory kinds — the reference's
cpu_offload path (group_sharded_stage3.py:85). The compiled train step
streams them over PCIe/host DMA at the step boundary; XLA schedules the
prefetch so transfers overlap compute (the reference's manual prefetch
thread collapses into the compiler's latency hiding).

``buffer_max_size``/``segment_size`` (grad storage coalescing) are XLA's
job — buffer assignment already coalesces; non-default values warn that
they are no-ops here rather than being silently discarded.
"""
from __future__ import annotations

import warnings
from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .auto_parallel import Replicate, Shard, shard_tensor
from .collective import Group
from .mesh import MeshRuntime


def _divisible_dim(shape, degree):
    """First dim the axis degree divides (dim0 preferred), else None.

    Delegates to ``analysis.sharding.divisible_dim`` — the static SH201/
    SH204 checks and the runtime placement policy must agree on which dim
    a parameter shards over (lazy import: analysis loads after this
    package in ``paddle_tpu/__init__``).
    """
    from ..analysis.sharding import divisible_dim
    return divisible_dim(shape, degree)


def _placements(mesh, axis, shard_dim):
    return [Shard(shard_dim) if n == axis else Replicate()
            for n in mesh.dim_names]


def _repl_placements(mesh):
    return [Replicate() for _ in mesh.dim_names]


class _ShardingStrategy:
    """Attached to the optimizer; consumed by TrainStep to constrain grads."""

    def __init__(self, level, mesh, axis, offload=False):
        self.level = level
        self.mesh = mesh
        self.axis = axis
        self.offload = offload

    def grad_sharding(self, shape):
        if self.level not in ("os_g", "p_g_os"):
            return None
        dim = _divisible_dim(shape, self.mesh.get_dim_size(self.axis))
        if dim is None:
            return None
        spec = [None] * len(shape)
        spec[dim] = self.axis
        return NamedSharding(self.mesh.jax_mesh, PartitionSpec(*spec))


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None, exclude_layer=None):
    """distributed/sharding/group_sharded.py analog. level: os | os_g | p_g_os."""
    assert level in ("os", "os_g", "p_g_os"), f"bad sharding level {level}"
    if offload and level == "os":
        raise ValueError("offload needs level 'os_g' or 'p_g_os' "
                         "(reference group_sharded.py constraint)")
    if buffer_max_size != 2 ** 23 or segment_size != 2 ** 20:
        warnings.warn(
            "group_sharded_parallel: buffer_max_size/segment_size are no-ops "
            "on the XLA backend (buffer assignment already coalesces "
            "gradient storage)", stacklevel=2)
    # the mesh runtime owns the "which mesh/axis does ZeRO shard over"
    # derivation (hybrid 'sharding' axis when fleet armed one, else the
    # given/world group's own axis)
    mesh, axis = MeshRuntime.sharding_axis(group)
    degree = mesh.get_dim_size(axis)

    # parameters: stage 3 shards them over the axis; else replicate
    replicated = []
    for p in model.parameters():
        if p._dist_attr is not None and any(
                not pl.is_replicate() for pl in p._dist_attr["placements"]):
            continue  # TP-annotated params keep their placement
        dim = _divisible_dim(p.shape, degree) if p.ndim > 0 else None
        if level == "p_g_os" and dim is not None:
            shard_tensor(p, mesh, _placements(mesh, axis, dim))
        else:
            if level == "p_g_os" and p.ndim > 0:
                replicated.append(getattr(p, "name", None) or str(p.shape))
            shard_tensor(p, mesh, _repl_placements(mesh))
    if replicated:
        warnings.warn(
            f"group_sharded_parallel(p_g_os): {len(replicated)} param(s) "
            f"have no dim divisible by the sharding degree {degree} and "
            f"stay replicated: {replicated[:5]}"
            + ("..." if len(replicated) > 5 else ""), stacklevel=2)

    # optimizer states: sharded for every stage; host-offloaded on request
    from ._shard_states import shard_optimizer_states
    shard_optimizer_states(optimizer, mesh, axis, offload=offload)
    optimizer._group_sharded = _ShardingStrategy(level, mesh, axis, offload)

    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework import io as fio
    fio.save(model.state_dict(), output + ".pdmodel.pdparams")
    if optimizer is not None:
        fio.save(optimizer.state_dict(), output + ".pdopt")
