"""Group-sharded (ZeRO) parallelism.

Reference: paddle.distributed.sharding.group_sharded_parallel
(distributed/sharding/group_sharded.py) dispatching to GroupShardedStage2
(grad+optimizer sharding, group_sharded_stage2.py:46) and GroupShardedStage3
(parameter sharding with prefetch, group_sharded_stage3.py:85); stage 1 via
DygraphShardingOptimizer (optimizer-state sharding).

TPU-native: ZeRO stages are PLACEMENT POLICIES over a 'sharding' mesh axis —
  stage 1 (os):    optimizer states Shard(0) over the axis
  stage 2 (os_g):  + gradients annotated Shard(0) (reduce-scatter backward)
  stage 3 (p_g_os):+ parameters Shard(0); XLA all-gathers params where used
                    and frees the gathered copies (prefetch/overlap is the
                    scheduler's job). No gather hooks, no storage coalescing.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .auto_parallel import Replicate, Shard, shard_tensor
from .collective import Group, init_parallel_env
from .fleet.topology import get_hybrid_communicate_group


def _sharding_mesh_axis(group: Optional[Group]):
    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        return hcg.mesh, "sharding"
    g = group or init_parallel_env()
    return g.mesh, g.axis_name


def _shard0_placements(mesh, axis):
    return [Shard(0) if n == axis else Replicate() for n in mesh.dim_names]


def _repl_placements(mesh):
    return [Replicate() for _ in mesh.dim_names]


class _ShardingStrategy:
    """Attached to the optimizer; consumed by TrainStep to constrain grads."""

    def __init__(self, level, mesh, axis):
        self.level = level
        self.mesh = mesh
        self.axis = axis

    def grad_sharding(self, shape):
        if self.level in ("os_g", "p_g_os") and shape and \
                shape[0] % self.mesh.get_dim_size(self.axis) == 0:
            from jax.sharding import NamedSharding, PartitionSpec
            return NamedSharding(self.mesh.jax_mesh, PartitionSpec(self.axis))
        return None


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """distributed/sharding/group_sharded.py analog. level: os | os_g | p_g_os."""
    assert level in ("os", "os_g", "p_g_os"), f"bad sharding level {level}"
    mesh, axis = _sharding_mesh_axis(group)
    degree = mesh.get_dim_size(axis)

    # parameters: stage 3 shards them over the axis; else replicate
    for p in model.parameters():
        if p._dist_attr is not None and any(
                not pl.is_replicate() for pl in p._dist_attr["placements"]):
            continue  # TP-annotated params keep their placement
        if level == "p_g_os" and p.ndim > 0 and p.shape[0] % degree == 0:
            shard_tensor(p, mesh, _shard0_placements(mesh, axis))
        else:
            shard_tensor(p, mesh, _repl_placements(mesh))

    # optimizer states: sharded for every stage
    from ._shard_states import shard_optimizer_states
    shard_optimizer_states(optimizer, mesh, axis)
    optimizer._group_sharded = _ShardingStrategy(level, mesh, axis)

    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework import io as fio
    fio.save(model.state_dict(), output + ".pdmodel.pdparams")
    if optimizer is not None:
        fio.save(optimizer.state_dict(), output + ".pdopt")
